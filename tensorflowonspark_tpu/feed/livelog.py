"""Crash-safe rotating live-traffic log: serving → training data.

The online continual loop (docs/ROBUSTNESS.md "Online continual
loop") needs serving traffic to BECOME training data while both
planes keep running. This module is the serve-side half: a
:class:`TrafficLog` that fleet replicas / ``serve_model`` feed with
one record per completed request, written as 64-aligned columnar
frames (``feed/columnar.py`` — the exact format ``FileManifest(
format="columnar")`` reads back zero-copy), rotated into sealed
segment files whose JSON manifests the driver's online loop
discovers (:func:`discover_manifests`) and appends to the RUNNING
ingest plan.

Hard rules, in order:

1. **Never block the serve path.** :meth:`TrafficLog.append` is one
   lock + a buffered frame write; any failure (disk full, armed
   ``online.log_append`` failpoint, closed log) DROPS the record and
   counts it in ``online_records_dropped_total{reason}`` — lost data
   is counted, never lied about, and never a request error.
2. **Crash-safe.** The active segment is append-only self-framing
   bytes: a SIGKILL mid-write leaves at most one torn tail frame,
   which the CRC codec rejects — :func:`TrafficLog.recover` (run at
   construction) truncates the tear, seals the rest, and republishes
   any sealed segment whose manifest publication was lost. Manifests
   are written tmp + ``os.replace`` so a reader never sees a torn
   JSON file (wire schema ``livelog.manifest``).
3. **Bounded disk.** ``disk_budget_bytes`` caps sealed-segment bytes
   with drop-oldest semantics: the oldest sealed segment (and its
   manifest) is deleted and its records counted as dropped
   (``reason="disk_budget"``). A stalled trainer therefore bounds log
   growth at the budget — the loop degrades to a sliding window of
   the freshest traffic instead of filling the disk.

Records are columnized with FIXED widths (the columnar codec rejects
ragged rows): token ids pad to ``prompt_width``/``completion_width``
int32 columns with explicit ``*_len`` columns, and the version/trace
stamps pad to fixed-width space-padded strings (trailing NULs would
be trimmed by numpy's S/U dtypes). :func:`decode_records` undoes the
padding for consumers that want the original shapes back.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.obs import flightrec
from tensorflowonspark_tpu.utils.failpoints import failpoint

logger = logging.getLogger(__name__)

__all__ = [
    "TrafficLog",
    "decode_records",
    "discover_manifests",
    "manifest_to_file",
]

#: Fixed column widths for the string stamps (space-padded; a version
#: or trace id longer than this is truncated — stamps are short ids,
#: not payloads).
VERSION_WIDTH = 24
TRACE_WIDTH = 24

_MANIFEST_DIR = "manifests"
_ACTIVE_SUFFIX = ".tfc.active"
_SEALED_SUFFIX = ".tfc"

_metrics_lock = threading.Lock()
_metrics: dict[str, Any] | None = None


def metrics() -> dict[str, Any]:
    """Traffic-log counters in the process-global obs registry."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from tensorflowonspark_tpu.obs.registry import (
                    default_registry,
                )

                r = default_registry()
                _metrics = {
                    "frames": r.counter(
                        "online_frames_logged_total",
                        "columnar frames appended to the live-traffic "
                        "log",
                    ),
                    "dropped": r.counter(
                        "online_records_dropped_total",
                        "live-traffic records dropped instead of "
                        "logged, by reason (failpoint|io_error|closed|"
                        "disk_budget); nonzero is lost training data — "
                        "counted, never lied about",
                    ),
                }
    return _metrics


def _pad_tokens(tokens: Any, width: int) -> tuple[np.ndarray, int]:
    arr = np.asarray(list(tokens) if tokens is not None else [], np.int32)
    n = min(int(arr.shape[0]), width)
    out = np.zeros((width,), np.int32)
    out[:n] = arr[:n]
    return out, n


def _pad_str(s: str | None, width: int) -> str:
    s = "" if s is None else str(s)
    return (s[:width]).ljust(width)


class TrafficLog:
    """Rotating columnar frame writer for per-request traffic records.

    ``root`` is the log directory (one per serving process — segment
    names embed ``stream``, so several logs may share a manifest
    consumer but never a directory). ``announce`` is an optional
    callback invoked with each published manifest dict — the hook a
    node uses to push a ``kv.livelog_announce`` discovery hint to the
    driver KV; discovery itself needs only the shared filesystem.
    """

    def __init__(
        self,
        root: str,
        *,
        stream: str = "live",
        prompt_width: int = 32,
        completion_width: int = 32,
        frame_records: int = 32,
        rotate_records: int = 256,
        rotate_seconds: float | None = None,
        disk_budget_bytes: int | None = None,
        announce: Callable[[dict], None] | None = None,
    ):
        if rotate_records < 1 or frame_records < 1:
            raise ValueError("rotate_records/frame_records must be >= 1")
        self.root = os.path.abspath(root)
        self.stream = str(stream)
        self.prompt_width = int(prompt_width)
        self.completion_width = int(completion_width)
        self.frame_records = int(frame_records)
        self.rotate_records = int(rotate_records)
        self.rotate_seconds = rotate_seconds
        self.disk_budget_bytes = disk_budget_bytes
        self.announce = announce
        os.makedirs(os.path.join(self.root, _MANIFEST_DIR), exist_ok=True)
        self._lock = threading.Lock()
        self._buf: list[dict] = []  # guarded-by: self._lock
        self._file = None  # open active segment  # guarded-by: self._lock
        self._seq = 0  # next segment seq  # guarded-by: self._lock
        self._frame_seq = 0  # within segment  # guarded-by: self._lock
        self._seg_records = 0  # guarded-by: self._lock
        self._seg_opened = 0.0  # wall clock  # guarded-by: self._lock
        self._seg_first: float | None = None  # guarded-by: self._lock
        self._seg_last: float | None = None  # guarded-by: self._lock
        # sealed segments still on disk, oldest first:
        # [(seq, path, manifest_path, bytes, records)]
        self._sealed: list[tuple] = []  # guarded-by: self._lock
        self._closed = False  # guarded-by: self._lock
        self.recover()

    # -- naming --------------------------------------------------------

    def _seg_name(self, seq: int) -> str:
        return f"{self.stream}-{seq:08d}"

    def _active_path(self, seq: int) -> str:
        return os.path.join(self.root, self._seg_name(seq) + _ACTIVE_SUFFIX)

    def _sealed_path(self, seq: int) -> str:
        return os.path.join(self.root, self._seg_name(seq) + _SEALED_SUFFIX)

    def _manifest_path(self, seq: int) -> str:
        return os.path.join(
            self.root, _MANIFEST_DIR, self._seg_name(seq) + ".json"
        )

    # -- serve-path append ---------------------------------------------

    def append(
        self,
        prompt: Any,
        completion: Any,
        *,
        outcome: float = 0.0,
        weights_version: str | None = None,
        trace_id: str | None = None,
    ) -> bool:
        """Log one completed request; returns False when the record
        was dropped (counted). NEVER raises and never blocks beyond
        one buffered frame write — the serve path's latency is the
        priority, the record is best-effort."""
        if failpoint("online.log_append") == "drop":
            metrics()["dropped"].inc(reason="failpoint")
            return False
        p, p_len = _pad_tokens(prompt, self.prompt_width)
        c, c_len = _pad_tokens(completion, self.completion_width)
        now = time.time()
        record = {
            "t_unix": np.float64(now),
            "prompt": p,
            "prompt_len": np.int32(p_len),
            "completion": c,
            "completion_len": np.int32(c_len),
            "outcome": np.float64(outcome),
            "weights_version": _pad_str(weights_version, VERSION_WIDTH),
            "trace_id": _pad_str(trace_id, TRACE_WIDTH),
        }
        with self._lock:
            if self._closed:
                metrics()["dropped"].inc(reason="closed")
                return False
            self._buf.append(record)
            if self._seg_first is None:
                self._seg_first = now
            self._seg_last = now
            try:
                if len(self._buf) >= self.frame_records:
                    self._flush_locked()
                if self._rotation_due_locked(now):
                    self._seal_locked()
            except (OSError, ValueError) as e:
                lost = len(self._buf)
                self._buf = []
                metrics()["dropped"].inc(lost, reason="io_error")
                logger.warning(
                    "traffic log append failed (%s): dropped %d "
                    "buffered record(s) — serve path unaffected",
                    e,
                    lost,
                )
                return False
        return True

    def _rotation_due_locked(self, now: float) -> bool:  # lint: holds-lock
        # count buffered records too: with rotate_records below the
        # frame size, rotation is what forces the flush
        pending = self._seg_records + len(self._buf)
        if pending >= self.rotate_records:
            return True
        return (
            self.rotate_seconds is not None
            and pending > 0
            and now - self._seg_opened >= self.rotate_seconds
        )

    def _flush_locked(self) -> None:  # lint: holds-lock
        """Columnize the buffered records into ONE frame and append it
        to the active segment (opened lazily)."""
        from tensorflowonspark_tpu.feed.columnar import (
            _PAD,
            _align,
            columnize_records,
            frame_bytes,
        )

        if not self._buf:
            return
        batch, self._buf = self._buf, []
        chunk = columnize_records(batch)
        if chunk is None:  # fixed widths make this unreachable in
            # practice; treat like any other io failure if it happens
            raise ValueError("traffic records failed to columnize")
        if self._file is None:
            self._file = open(self._active_path(self._seq), "ab")
            self._seg_opened = time.time()
        data = frame_bytes(
            chunk,
            stream=self._seg_name(self._seq),
            seq=self._frame_seq,
        )
        self._file.write(data)
        self._file.write(_PAD[: _align(len(data)) - len(data)])
        self._file.flush()
        self._frame_seq += 1
        self._seg_records += len(batch)
        metrics()["frames"].inc()

    # -- rotation / sealing --------------------------------------------

    def rotate(self) -> dict | None:
        """Seal the active segment now (if it has records) and publish
        its manifest; returns the manifest dict or None when the
        segment was empty. The driver-facing flush hook — the online
        loop calls it so a slow trickle of traffic still becomes
        training data each cycle."""
        with self._lock:
            if self._closed:
                return None
            try:
                return self._seal_locked()
            except (OSError, ValueError) as e:
                lost = len(self._buf)
                self._buf = []
                if lost:
                    metrics()["dropped"].inc(lost, reason="io_error")
                logger.warning("traffic log rotate failed (%s)", e)
                return None

    def _seal_locked(self) -> dict | None:  # lint: holds-lock
        self._flush_locked()
        if self._seg_records == 0:
            return None
        seq = self._seq
        f, self._file = self._file, None
        f.flush()
        os.fsync(f.fileno())
        f.close()
        sealed = self._sealed_path(seq)
        os.replace(self._active_path(seq), sealed)
        records = self._seg_records
        first, last = self._seg_first, self._seg_last
        self._seq += 1
        self._frame_seq = 0
        self._seg_records = 0
        self._seg_first = self._seg_last = None
        manifest = self._publish_locked(
            seq, sealed, records, first=first, last=last
        )
        self._enforce_budget_locked()
        return manifest

    def _publish_locked(
        self,
        seq: int,
        sealed: str,
        records: int,
        first: float | None = None,
        last: float | None = None,
    ) -> dict | None:  # lint: holds-lock
        nbytes = os.path.getsize(sealed)
        manifest = wire.encode(
            "livelog.manifest",
            path=sealed,
            records=int(records),
            bytes=int(nbytes),
            seq=int(seq),
            stream=self.stream,
            sealed_unix=time.time(),
            first_unix=first,
            last_unix=last,
        )
        mpath = self._manifest_path(seq)
        if failpoint("online.manifest_publish") == "drop":
            # a lost publication: the sealed segment stays on disk,
            # undiscovered until recover() republishes it — bounded
            # staleness, never lost data
            logger.warning(
                "traffic log manifest publication for segment %d "
                "dropped (failpoint online.manifest_publish) — "
                "recover() will republish",
                seq,
            )
            self._sealed.append((seq, sealed, mpath, nbytes, records))
            return None
        tmp = f"{mpath}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as mf:
            json.dump(manifest, mf)
            mf.write("\n")
        os.replace(tmp, mpath)
        self._sealed.append((seq, sealed, mpath, nbytes, records))
        flightrec.note(
            "online_manifest_publish",
            stream=self.stream,
            seq=seq,
            records=records,
            bytes=nbytes,
        )
        if self.announce is not None:
            try:
                self.announce(manifest)
            except Exception as e:  # noqa: BLE001 - announce is a hint
                logger.warning("traffic log announce failed (%s)", e)
        return manifest

    def _enforce_budget_locked(self) -> None:  # lint: holds-lock
        if self.disk_budget_bytes is None:
            return
        total = sum(s[3] for s in self._sealed)
        while len(self._sealed) > 1 and total > self.disk_budget_bytes:
            seq, path, mpath, nbytes, records = self._sealed.pop(0)
            total -= nbytes
            for p in (path, mpath):
                try:
                    os.remove(p)
                except OSError:
                    pass
            metrics()["dropped"].inc(records, reason="disk_budget")
            logger.warning(
                "traffic log over disk budget: dropped oldest sealed "
                "segment %d (%d record(s), %d bytes) — a lagging "
                "trainer sees a sliding window, not unbounded disk",
                seq,
                records,
                nbytes,
            )

    def sealed_bytes(self) -> int:
        """Total bytes of sealed segments still on disk (the quantity
        the disk budget caps) — the loop's stall-detection input."""
        with self._lock:
            return sum(s[3] for s in self._sealed)

    # -- recovery ------------------------------------------------------

    def recover(self) -> int:
        """Crash recovery (also run at construction): truncate the torn
        tail frame of any leftover ``.active`` segment, seal what
        survives, republish manifests lost before the crash, and resume
        numbering after the highest existing segment. Returns the
        number of segments recovered or republished."""
        from tensorflowonspark_tpu.feed.columnar import decode_frame

        fixed = 0
        with self._lock:
            by_seq: dict[int, str] = {}
            for fn in sorted(os.listdir(self.root)):
                if not fn.startswith(self.stream + "-"):
                    continue
                stem = fn[len(self.stream) + 1 :]
                if fn.endswith(_ACTIVE_SUFFIX):
                    seqs = stem[: -len(_ACTIVE_SUFFIX)]
                elif fn.endswith(_SEALED_SUFFIX):
                    seqs = stem[: -len(_SEALED_SUFFIX)]
                else:
                    continue
                try:
                    by_seq[int(seqs)] = os.path.join(self.root, fn)
                except ValueError:
                    continue
            for seq in sorted(by_seq):
                path = by_seq[seq]
                if path.endswith(_ACTIVE_SUFFIX):
                    good, records = _scan_intact(path, decode_frame)
                    size = os.path.getsize(path)
                    if good < size:
                        with open(path, "r+b") as f:
                            f.truncate(good)
                        logger.warning(
                            "traffic log recovery: truncated torn "
                            "tail of %s (%d -> %d bytes)",
                            path,
                            size,
                            good,
                        )
                    if records == 0:
                        os.remove(path)
                        continue
                    sealed = self._sealed_path(seq)
                    os.replace(path, sealed)
                    self._publish_locked(seq, sealed, records)
                    fixed += 1
                elif not os.path.exists(self._manifest_path(seq)):
                    # sealed before the crash, manifest publication
                    # lost (or dropped by the failpoint): republish
                    _, records = _scan_intact(path, decode_frame)
                    self._publish_locked(seq, path, records)
                    fixed += 1
                else:
                    records = _manifest_records(self._manifest_path(seq))
                    self._sealed.append(
                        (
                            seq,
                            path,
                            self._manifest_path(seq),
                            os.path.getsize(path),
                            records,
                        )
                    )
            if by_seq:
                self._seq = max(by_seq) + 1
            self._enforce_budget_locked()
        return fixed

    # -- lifecycle -----------------------------------------------------

    def close(self, seal: bool = True) -> None:
        """Stop accepting records; ``seal=True`` (default) publishes
        the in-progress segment so buffered traffic is not stranded."""
        with self._lock:
            if self._closed:
                return
            try:
                if seal:
                    self._seal_locked()
                elif self._file is not None:
                    self._file.close()
            except (OSError, ValueError) as e:
                logger.warning("traffic log close failed (%s)", e)
            finally:
                self._file = None
                self._closed = True


def _scan_intact(path: str, decode_frame) -> tuple[int, int]:
    """(intact_byte_length, record_count) of a framed file: walk frames
    from the head, fully CRC-verifying each; stop at the first torn /
    truncated / corrupt frame."""
    from tensorflowonspark_tpu.feed.columnar import (
        _PREFIX,
        _align,
        frame_span,
    )

    good = 0
    records = 0
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return 0, 0
    size = len(data)
    mv = memoryview(data)
    while good + _PREFIX.size <= size:
        try:
            span = frame_span(mv, good)
            if good + span > size:
                break  # truncated mid-payload
            # decode_frame verifies header + payload CRCs; a torn tail
            # fails here (short buffers / bit flips → ValueError)
            chunk = decode_frame(mv[good : good + span])
            records += len(chunk)
            good += _align(span)
        except Exception:  # noqa: BLE001 - any tear ends the scan
            break
    return good, records


def _manifest_records(mpath: str) -> int:
    try:
        with open(mpath, encoding="utf-8") as f:
            return int(json.load(f).get("records", 0))
    except (OSError, ValueError):
        return 0


# -- driver-side discovery ---------------------------------------------------


def discover_manifests(
    root: str, *, after_seq: int = -1, stream: str | None = None
) -> list[dict]:
    """Scan a traffic log's manifest directory and return the decoded
    manifests with ``seq > after_seq``, ordered by seq — the driver
    loop's per-poll discovery step. A torn or malformed manifest file
    is skipped loudly (the writer publishes atomically, so this only
    happens to foreign files)."""
    failpoint("online.discover")
    mdir = os.path.join(os.path.abspath(root), _MANIFEST_DIR)
    out: list[dict] = []
    try:
        names = sorted(os.listdir(mdir))
    except OSError:
        return out
    for fn in names:
        if not fn.endswith(".json"):
            continue
        path = os.path.join(mdir, fn)
        try:
            with open(path, encoding="utf-8") as f:
                m = wire.decode("livelog.manifest", json.load(f))
        except (OSError, ValueError, wire.WireError) as e:
            logger.warning(
                "skipping malformed traffic-log manifest %s (%s)", path, e
            )
            continue
        if m["seq"] <= after_seq:
            continue
        if stream is not None and m["stream"] != stream:
            continue
        out.append(m)
    out.sort(key=lambda m: (m["stream"], m["seq"]))
    return out


def manifest_to_file(m: dict) -> Any:
    """A published livelog manifest as the ``FileManifest`` the ingest
    plane plans and reads (``format="columnar"``)."""
    from tensorflowonspark_tpu.feed.manifest import FileManifest

    return FileManifest(path=m["path"], format="columnar")


def decode_records(rows: Iterator[Any]) -> Iterator[dict]:
    """Undo the fixed-width padding: yields dicts with ``prompt`` /
    ``completion`` trimmed to their true lengths and the string stamps
    stripped — the trainer-side view of logged traffic."""
    for r in rows:
        p_len = int(r["prompt_len"])
        c_len = int(r["completion_len"])
        yield {
            "t_unix": float(r["t_unix"]),
            "prompt": np.asarray(r["prompt"])[:p_len],
            "completion": np.asarray(r["completion"])[:c_len],
            "outcome": float(r["outcome"]),
            "weights_version": str(r["weights_version"]).rstrip(),
            "trace_id": str(r["trace_id"]).rstrip(),
        }
