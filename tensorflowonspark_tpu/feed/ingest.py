"""Driverless pull ingestion — executor-local sharded columnar readers.

BASELINE.md's push-plane ceiling shows why this module exists: every
byte of ``InputMode.SPARK`` crosses the single driver process, and the
measured aggregate *collapses* as the cluster grows (661 MB/s at 4
nodes → 344 at 8). The reference never had the problem because its feed
tasks ran on the executors with HDFS locality — the driver shipped
closures, not bytes (SURVEY.md §3.2); tf.data (arXiv:2101.12127) makes
the same move with source sharding + per-host pipelines, and the
TensorFlow system paper (arXiv:1605.08695) argues for keeping the
coordinator off the data path entirely.

This module is that shape for ``InputMode.TENSORFLOW``: the driver
ships only partition *manifests* (``TFCluster.assign_shards`` →
``feed.manifest.plan_manifests`` → one tiny plan per node over the
manager KV), and each node opens, reads, and columnizes its own shard
locally:

- :class:`ShardReader` iterates a shard's pieces. ``'columnar'``
  manifests (the CRC-framed files from ``feed/columnar.py`` — the
  ready-made on-disk wire format) decode to **zero-copy column views
  over one shared mmap**; other formats stream rows through
  ``data.readers.columnar_pieces`` (block columnization where the data
  lives, with the same row-list fallback matrix as the push wire).
- :class:`IngestFeed` is the ``DataFeed``-shaped consumer: the same
  slice-not-stack batch assembly (``ColumnAssembler``), the same
  ``batch_stream`` contract, and therefore the same
  ``DevicePrefetcher.from_feed`` staging — a training loop moves from
  push to pull by swapping ``ctx.get_data_feed()`` for
  ``ctx.get_ingest_feed()``.

**Exactly-once + ordering.** Every piece of one shard stream carries a
deterministic ``(stream, seq)`` — the stream id is a pure function of
what is read (:func:`stream_id`: path + record range), the seq is the
block ordinal — checked by the same :class:`~tensorflowonspark_tpu.
feed.datafeed.ReplayCursor` protocol as the push wire: duplicates
(a retried shard read, a restarted node re-reading its shard, an
elastic re-plan) drop silently, forward gaps (a lost block — see the
``ingest.read_block`` failpoint) raise. ``IngestFeed.cursor()``
returns only FULLY-consumed blocks (pieces still buffered in the
assembler are excluded), so a consumer that checkpoints the cursor
beside its train state and later seeds a fresh feed
(:meth:`IngestFeed.seed_cursor`) replays with zero duplicates and zero
holes, mid-shard.

Transient read failures retry in place (``RetryPolicy`` backoff; the
replay cursor makes the re-read idempotent); non-retryable failures
propagate and the node relaunch path (``run_with_restarts`` / elastic
supervise) takes over — the successor seeds its cursor and resumes.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Sequence

from tensorflowonspark_tpu.feed.columnar import ColumnAssembler, ColumnChunk
from tensorflowonspark_tpu.feed.datafeed import ReplayCursor, columnize_rows
from tensorflowonspark_tpu.feed.manifest import (
    FileManifest,
    read_manifest,
    read_manifest_chunks,
)
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils.failpoints import FailpointError, failpoint
from tensorflowonspark_tpu.utils.retry import DEFAULT_RETRYABLE, RetryPolicy

logger = logging.getLogger(__name__)

__all__ = ["IngestFeed", "RowPiece", "ShardReader", "metrics", "stream_id"]

# Read faults a shard read retries in place. FailpointError is included
# deliberately: the ``ingest.open_shard`` / ``ingest.read_block`` chaos
# sites exercise exactly this loop (docs/ROBUSTNESS.md failpoint
# conventions — a site opts into retrying injected faults).
_RETRYABLE = DEFAULT_RETRYABLE + (FailpointError,)


# -- obs ---------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: dict[str, Any] | None = None


def metrics() -> dict[str, Any]:
    """Pull-plane ingest counters in the process-global obs registry:
    shard files opened, column-payload bytes and records delivered by
    THIS node's executor-local readers. The driver-side
    ``MetricsAggregator`` differentiates ``feed_ingest_bytes_total``
    between scrapes into the per-node ``cluster_node_ingest_bytes_per_s``
    gauge — the scaling bench's "per-node throughput flat" criterion,
    readable straight off the registry."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from tensorflowonspark_tpu.obs.registry import default_registry

                r = default_registry()
                _metrics = {
                    "files": r.counter(
                        "feed_ingest_files_total",
                        "shard files opened by executor-local readers, "
                        "by format",
                    ),
                    "bytes": r.counter(
                        "feed_ingest_bytes_total",
                        "column-payload bytes ingested by executor-local "
                        "readers",
                    ),
                    "records": r.counter(
                        "feed_ingest_records_total",
                        "records ingested by executor-local readers",
                    ),
                }
    return _metrics


# -- stream identity ---------------------------------------------------------


def stream_id(m: Any) -> str:
    """Deterministic replay-stream id for one manifest: a pure function
    of WHAT is read (path + record range), never of when or by whom —
    a restarted reader, a relaunched node, or an elastic re-plan
    re-derives the same id, which is what lets a seeded
    :class:`ReplayCursor` recognize the already-consumed prefix."""
    if isinstance(m, FileManifest):
        stop = "" if m.stop is None else int(m.stop)
        return f"{m.path}@{int(m.start)}:{stop}"
    return f"manifest:{m!r}"


class RowPiece(list):
    """A row-list piece (the non-columnizable fallback) stamped with
    its ``(stream, seq)`` so the consumed-cursor bookkeeping survives
    the fallback path; slicing preserves the stamp (the assembler
    splits head pieces across batches)."""

    __slots__ = ("stream", "seq")

    def __init__(self, rows: Sequence[Any], stream: str | None = None, seq: int = 0):
        super().__init__(rows)
        self.stream = stream
        self.seq = seq

    def __getitem__(self, i):
        out = super().__getitem__(i)
        if isinstance(i, slice):
            return RowPiece(out, self.stream, self.seq)
        return out


# -- executor-local reading --------------------------------------------------


class ShardReader:
    """Reads one node's shard — a list of manifests — locally, yielding
    stamped pieces (``ColumnChunk`` views / :class:`RowPiece` lists).

    Manifests are read sequentially (ordering is part of the replay
    contract); each manifest is one replay stream whose blocks carry
    ordinal ``seq``. A transient failure (``_RETRYABLE``) mid-manifest
    restarts that manifest's read under the jittered ``retry`` policy —
    the caller's :class:`ReplayCursor` drops the re-read prefix, so a
    retry can neither duplicate nor skip records (the ``ingest.
    open_shard`` / ``ingest.read_block`` failpoints exercise this).
    """

    def __init__(
        self,
        manifests: Sequence[Any],
        reader: Callable[[Any], Iterator[Any]] | None = None,
        records_per_chunk: int = 1024,
        retry: RetryPolicy | None = None,
    ):
        self.manifests = list(manifests)
        self.reader = reader
        self.records_per_chunk = int(records_per_chunk)
        self.retry = (
            retry
            if retry is not None
            else RetryPolicy(max_attempts=3, deadline_s=120.0)
        )

    def pieces(self, cursor: ReplayCursor) -> Iterator[Any]:
        """All pieces of this shard, in manifest order, deduped/ordered
        through ``cursor``."""
        for m in self.manifests:
            yield from self._manifest_pieces(m, cursor)

    def _manifest_pieces(self, m: Any, cursor: ReplayCursor) -> Iterator[Any]:
        # Hand-rolled rather than RetryPolicy.call: the body is a
        # GENERATOR (pieces stream out between faults), which a
        # callable-wrapping retry cannot express. The policy's
        # invariants are preserved: its jittered schedule, its counter,
        # and its deadline — a sleep never starts at or past the
        # deadline, and never overshoots it.
        from tensorflowonspark_tpu.utils.retry import _retry_counter

        delays = self.retry.delays()
        deadline = (
            None
            if self.retry.deadline_s is None
            else time.monotonic() + self.retry.deadline_s
        )
        attempt = 0
        while True:
            attempt += 1
            try:
                yield from self._read_once(m, cursor)
                return
            except _RETRYABLE as e:
                delay = next(delays, None)
                if delay is None:
                    raise
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise
                    delay = min(delay, remaining)
                _retry_counter().inc(site="ingest.shard")
                logger.warning(
                    "ingest: shard %s read failed (%s: %s); retrying "
                    "(attempt %d/%d) — the replay cursor drops re-read "
                    "blocks",
                    getattr(m, "path", m),
                    type(e).__name__,
                    e,
                    attempt,
                    self.retry.max_attempts,
                )
                time.sleep(delay)

    def _raw_pieces(self, m: Any) -> Iterator[Any]:
        if (
            self.reader is None
            and isinstance(m, FileManifest)
            and m.format == "columnar"
        ):
            # the on-disk wire format: zero-copy views over one mmap,
            # payload-CRC-verified per frame
            yield from read_manifest_chunks(m)
            return
        from tensorflowonspark_tpu.data.readers import columnar_pieces

        yield from columnar_pieces(
            read_manifest(m, self.reader), self.records_per_chunk
        )

    def _read_once(self, m: Any, cursor: ReplayCursor) -> Iterator[Any]:
        met = metrics()
        sid = stream_id(m)
        fmt = m.format if isinstance(m, FileManifest) else "custom"
        failpoint("ingest.open_shard")
        met["files"].inc(format=fmt)
        # ingest.read is an externally-measured interval (spans.record's
        # synthetic lane), accumulated around the read steps only: a
        # call-stack span held open across yields would swallow the
        # consumer's compute between pulls into "read" time.
        read_s = 0.0
        n_records = 0
        raw = self._raw_pieces(m)
        seq = -1
        try:
            while True:
                t0 = time.perf_counter()
                piece = next(raw, None)
                read_s += time.perf_counter() - t0
                if piece is None:
                    return
                seq += 1
                if failpoint("ingest.read_block") == "drop":
                    # chaos: block lost mid-shard — the cursor's gap
                    # check on the NEXT block surfaces it loudly
                    continue
                if not cursor.check(sid, seq):
                    continue  # replayed duplicate (retry/restart/re-plan)
                if isinstance(piece, ColumnChunk):
                    piece = ColumnChunk(
                        piece.kind,
                        piece.keys,
                        piece.arrays,
                        qname=piece.qname,
                        stream=sid,
                        seq=seq,
                    )
                    met["bytes"].inc(piece.nbytes)
                else:
                    piece = RowPiece(piece, sid, seq)
                met["records"].inc(len(piece))
                n_records += len(piece)
                yield piece
                # no piece reference held across the next read — the
                # same liveness rule as the wire pull loops (mmap
                # pinning is milder than ring slots, but uniform rules
                # are checkable rules)
                piece = None
        finally:
            try:
                obs_spans.record(
                    "ingest.read",
                    read_s,
                    path=str(getattr(m, "path", m)),
                    format=fmt,
                    records=n_records,
                )
            except Exception:  # pragma: no cover - interpreter teardown
                pass  # an abandoned reader GC'd at exit must stay quiet


# -- the DataFeed-shaped consumer --------------------------------------------


class IngestFeed:
    """The pull plane's in-node consumer: ``DataFeed``'s surface
    (``next_batch`` / ``should_stop`` / ``batch_stream`` / ``cursor`` /
    ``seed_cursor`` / ``terminate``) over an executor-local
    :class:`ShardReader` — no queue, no driver, no bytes over the
    control plane.

    Construct directly from manifests, or via ``ctx.get_ingest_feed()``
    which fetches this node's shard from the driver-published plan
    (``TFCluster.assign_shards``). With an ``input_mapping`` batches
    are ``{tensor: ndarray}`` columns SLICED from the shard's chunks
    (zero-copy within one chunk); without one, plain record lists.
    Like ``ManifestFeed``, batches fill across file boundaries — steady
    jit shapes are the point of the plane.
    """

    def __init__(
        self,
        manifests: Sequence[Any],
        input_mapping: dict[str, str] | None = None,
        reader: Callable[[Any], Iterator[Any]] | None = None,
        records_per_chunk: int = 1024,
        retry: RetryPolicy | None = None,
        plan_epoch: int = 0,
        worker_index: int | None = None,
    ):
        self.input_mapping = input_mapping
        self.plan_epoch = int(plan_epoch)
        self.worker_index = worker_index
        self._reader = ShardReader(
            manifests,
            reader=reader,
            records_per_chunk=records_per_chunk,
            retry=retry,
        )
        from tensorflowonspark_tpu.feed.datafeed import _replay_counter

        self._seq = ReplayCursor(
            name=f"ingest shard (worker "
            f"{worker_index if worker_index is not None else '?'})",
            on_drop=lambda _s: _replay_counter().inc(queue="ingest"),
        )
        self._assembler = (
            ColumnAssembler(input_mapping) if input_mapping else None
        )
        self._buffer: list[Any] = []  # rows of a partially-consumed piece
        self._iter: Iterator[Any] | None = None
        self._exhausted = False
        # Exactly-once bookkeeping. Pieces enter assembly in FIFO order
        # and records leave it in the same order, so one cumulative
        # consumption count maps back to (fully-consumed blocks, record
        # offset into the in-progress block) — the record-exact cursor.
        # cursor() runs on the training/checkpoint thread while the
        # DevicePrefetcher producer thread advances consumption, so the
        # bookkeeping is lock-guarded (tfsan dogfood; a torn deque/dict
        # read here would checkpoint a cursor with holes).
        self._cursor_lock = threading.Lock()
        self._delivered: deque = deque()  # (stream, seq, length, base)  # guarded-by: self._cursor_lock
        self._head_consumed = 0  # records consumed from _delivered[0]  # guarded-by: self._cursor_lock
        # stream -> consumed state: int (last fully consumed seq) or
        # [seq, skip] (seeded mid-block state not yet superseded by
        # this feed's own progress)
        self._done: dict[str, Any] = {}  # guarded-by: self._cursor_lock
        self._pending_skip: dict[str, tuple[int, int]] = {}  # seeded offsets  # guarded-by: self._cursor_lock

    # -- replay cursor -------------------------------------------------
    def cursor(self) -> dict[str, Any]:
        """Record-exact consumption snapshot, per stream: ``seq`` when
        block ``seq`` is the last FULLY consumed one, or ``[seq, skip]``
        when additionally the first ``skip`` records of block
        ``seq + 1`` have left in batches. Records still buffered inside
        the feed (read but never batched out) are NOT counted — a
        successor seeded with this snapshot (:meth:`seed_cursor`)
        re-reads them: zero duplicates, zero holes, mid-shard and even
        mid-block. Checkpoint it beside the train state. Safe to call
        from any thread while the feed is being consumed."""
        with self._cursor_lock:
            out: dict[str, Any] = dict(self._done)
            if self._delivered and self._head_consumed:
                s, q, _ln, base = self._delivered[0]
                if s is not None:
                    out[s] = [q - 1, base + self._head_consumed]
            return out

    def seed_cursor(self, cursor: dict[str, Any]) -> None:
        """Adopt a :meth:`cursor` snapshot BEFORE consuming. Whole
        blocks at or below each stream's seeded seq drop as replayed
        duplicates on the re-read; a ``[seq, skip]`` entry additionally
        trims the first ``skip`` records off block ``seq + 1``. Plain
        ``{stream: seq}`` cursors (the push plane's ``DataFeed``
        format) are accepted unchanged.

        Seeded state is itself part of :meth:`cursor`'s output until
        this feed makes further progress on the stream: a successor
        that crashes before touching an already-consumed stream must
        still hand ITS successor the full consumed prefix — otherwise
        the third incarnation would replay whole streams (duplicates).
        """
        seed: dict[str, int] = {}
        with self._cursor_lock:
            for s, v in cursor.items():
                s = str(s)
                if isinstance(v, (list, tuple)):
                    seq0, skip = int(v[0]), int(v[1])
                else:
                    seq0, skip = int(v), 0
                if seq0 >= 0:
                    seed[s] = seq0
                if skip > 0:
                    self._pending_skip[s] = (seq0 + 1, skip)
                    self._done[s] = [seq0, skip]
                elif seq0 >= 0:
                    self._done[s] = seq0
        self._seq.seed(seed)

    # -- iteration core ------------------------------------------------
    def _pieces_iter(self) -> Iterator[Any]:
        if self._iter is None:
            self._iter = self._reader.pieces(self._seq)
        return self._iter

    def _pull_piece(self) -> Any | None:
        """Next piece off the reader, seeded-skip applied and delivery
        recorded for the consumed-cursor bookkeeping."""
        while not self._exhausted:
            piece = next(self._pieces_iter(), None)
            if piece is None:
                self._exhausted = True
                return None
            stream = getattr(piece, "stream", None)
            seq = int(getattr(piece, "seq", 0))
            base = 0
            if stream is not None:
                with self._cursor_lock:
                    sk = self._pending_skip.get(stream)
                    matched = sk is not None and sk[0] == seq
                    if matched:
                        del self._pending_skip[stream]
                if matched:
                    base = min(int(sk[1]), len(piece))
                    if base:
                        piece = (
                            piece.view(base, len(piece))
                            if isinstance(piece, ColumnChunk)
                            else RowPiece(list(piece)[base:], stream, seq)
                        )
            if len(piece):
                with self._cursor_lock:
                    self._delivered.append((stream, seq, len(piece), base))
                return piece
        return None

    def _advance_consumed(self, n: int) -> None:
        """Records left the feed in a batch (or were dropped at the
        tail): pop fully-consumed pieces off the delivery FIFO and
        advance the per-stream done cursor."""
        with self._cursor_lock:
            self._head_consumed += int(n)
            while self._delivered:
                s, q, ln, _base = self._delivered[0]
                if self._head_consumed < ln:
                    break
                self._delivered.popleft()
                self._head_consumed -= ln
                if s is not None:
                    self._done[s] = q

    def should_stop(self) -> bool:
        """True once the shard is exhausted AND every buffered record
        has left in a batch (``DataFeed.should_stop`` contract)."""
        return (
            self._exhausted
            and not self._buffer
            and (self._assembler is None or len(self._assembler) == 0)
        )

    def next_batch(self, batch_size: int) -> list | dict[str, Any]:
        """Up to ``batch_size`` records; partial only at shard end.
        Mapped feeds return sliced ``{tensor: column}`` dicts, mapping-
        less feeds record lists (``ColumnChunk.rows`` semantics, as on
        the push wire)."""
        if self._assembler is None:
            if self.input_mapping is not None:
                # degenerate empty mapping: legacy stacking contract
                return columnize_rows(
                    self._next_raw(batch_size), self.input_mapping
                )
            return self._next_raw(batch_size)
        asm = self._assembler
        while len(asm) < batch_size:
            piece = self._pull_piece()
            if piece is None:
                break
            asm.push(piece)
        n = min(batch_size, len(asm))
        out = asm.take(batch_size)
        self._advance_consumed(n)
        return out

    def _next_raw(self, batch_size: int, account: bool = True) -> list:
        """Up to ``batch_size`` raw records. ``account=False`` defers
        the consumed-cursor advance to the caller — rows handed to an
        intermediate buffer (``fixed_size_batches``) have NOT left the
        feed yet, and counting them consumed would punch resume holes."""
        batch: list[Any] = []
        while len(batch) < batch_size:
            take = batch_size - len(batch)
            if self._buffer:
                batch.extend(self._buffer[:take])
                del self._buffer[:take]
                continue
            piece = self._pull_piece()
            if piece is None:
                break
            if isinstance(piece, ColumnChunk):
                self._buffer.extend(piece.rows())
            else:
                self._buffer.extend(piece)
            piece = None
        if account:
            self._advance_consumed(len(batch))
        return batch

    def batch_stream(
        self,
        batch_size: int,
        multiple_of: int = 1,
        input_mapping: dict[str, str] | None = None,
    ):
        """Fixed-size batches with the ``DataFeed.batch_stream``
        contract: every yield has exactly ``batch_size`` records
        (rounded down to ``multiple_of``) until the shard tail, which
        trims to the largest multiple (sub-multiple remainder dropped
        with a log line). The mapping may come from the constructor
        (``DataFeed`` style) or here (``ManifestFeed`` style) — either
        way ``DevicePrefetcher.from_feed`` drives it unchanged."""
        mapping = (
            input_mapping if input_mapping is not None else self.input_mapping
        )
        if not mapping:
            from tensorflowonspark_tpu.utils.batching import fixed_size_batches

            # consumption is advanced per EMITTED batch, never when rows
            # merely enter fixed_size_batches' pending buffer — those
            # rows have not left the feed, and counting them consumed
            # would make a checkpointed cursor skip them on resume
            pulled = 0

            def records():
                nonlocal pulled
                while not self.should_stop():
                    rows = self._next_raw(batch_size, account=False)
                    if not rows:
                        return
                    pulled += len(rows)
                    yield from rows

            emitted = 0
            for batch in fixed_size_batches(
                records(),
                batch_size,
                multiple_of,
                assemble=lambda rows: list(rows),
            ):
                emitted += len(batch)
                self._advance_consumed(len(batch))
                yield batch
            # normal exhaustion: the sub-multiple remainder was DROPPED
            # (drop-remainder semantics) — dropped counts as consumed.
            # Unreached on an early generator close, where the pending
            # rows were never delivered and must replay.
            self._advance_consumed(pulled - emitted)
            return
        if self._assembler is None or self._assembler.mapping != mapping:
            old = self._assembler
            self._assembler = ColumnAssembler(dict(mapping))
            # FIFO order is the cursor's correctness invariant: oldest
            # unconsumed records (a prior mapping-less next_batch's row
            # buffer) re-enter assembly first.
            if self._buffer:
                self._assembler.push(list(self._buffer))
                self._buffer = []
            if old is not None:
                for piece in old.drain_pieces():
                    self._assembler.push(piece)
        bs = batch_size - batch_size % multiple_of
        if bs == 0:
            raise ValueError(
                f"batch_size < multiple_of ({multiple_of}); nothing to yield"
            )
        asm = self._assembler
        while True:
            while len(asm) < bs:
                piece = self._pull_piece()
                if piece is None:
                    break
                asm.push(piece)
            if len(asm) < bs:
                break
            batch = asm.take(bs)
            self._advance_consumed(bs)
            yield batch
        tail = len(asm) - len(asm) % multiple_of
        rem = len(asm) % multiple_of
        if rem:
            logger.warning(
                "dropping %d tail records (not a multiple of %d)",
                rem,
                multiple_of,
            )
        if tail:
            batch = asm.take(tail)
            self._advance_consumed(tail)
            yield batch
        if len(asm):
            # discard the sub-multiple remainder (drop-remainder
            # semantics, same as the push wire's column_batches) —
            # dropped counts as consumed: a resume must not replay it
            asm.take(len(asm))
            self._advance_consumed(rem)

    def terminate(self) -> None:
        """Stop reading (early stop). Purely local — there is no
        producer to signal on the pull plane."""
        self._exhausted = True
        it, self._iter = self._iter, None
        if it is not None and hasattr(it, "close"):
            it.close()
