"""Driverless pull ingestion — executor-local sharded columnar readers.

BASELINE.md's push-plane ceiling shows why this module exists: every
byte of ``InputMode.SPARK`` crosses the single driver process, and the
measured aggregate *collapses* as the cluster grows (661 MB/s at 4
nodes → 344 at 8). The reference never had the problem because its feed
tasks ran on the executors with HDFS locality — the driver shipped
closures, not bytes (SURVEY.md §3.2); tf.data (arXiv:2101.12127) makes
the same move with source sharding + per-host pipelines, and the
TensorFlow system paper (arXiv:1605.08695) argues for keeping the
coordinator off the data path entirely.

This module is that shape for ``InputMode.TENSORFLOW``: the driver
ships only partition *manifests* (``TFCluster.assign_shards`` →
``feed.manifest.plan_manifests`` → one tiny plan per node over the
manager KV), and each node opens, reads, and columnizes its own shard
locally:

- :class:`ShardReader` iterates a shard's pieces. ``'columnar'``
  manifests (the CRC-framed files from ``feed/columnar.py`` — the
  ready-made on-disk wire format) decode to **zero-copy column views
  over one shared mmap**; other formats stream rows through
  ``data.readers.columnar_pieces`` (block columnization where the data
  lives, with the same row-list fallback matrix as the push wire).
- :class:`IngestFeed` is the ``DataFeed``-shaped consumer: the same
  slice-not-stack batch assembly (``ColumnAssembler``), the same
  ``batch_stream`` contract, and therefore the same
  ``DevicePrefetcher.from_feed`` staging — a training loop moves from
  push to pull by swapping ``ctx.get_data_feed()`` for
  ``ctx.get_ingest_feed()``.

**Exactly-once + ordering.** Every piece of one shard stream carries a
deterministic ``(stream, seq)`` — the stream id is a pure function of
what is read (:func:`stream_id`: path + record range), the seq is the
block ordinal — checked by the same :class:`~tensorflowonspark_tpu.
feed.datafeed.ReplayCursor` protocol as the push wire: duplicates
(a retried shard read, a restarted node re-reading its shard, an
elastic re-plan) drop silently, forward gaps (a lost block — see the
``ingest.read_block`` failpoint) raise. ``IngestFeed.cursor()``
returns only FULLY-consumed blocks (pieces still buffered in the
assembler are excluded), so a consumer that checkpoints the cursor
beside its train state and later seeds a fresh feed
(:meth:`IngestFeed.seed_cursor`) replays with zero duplicates and zero
holes, mid-shard.

Transient read failures retry in place (``RetryPolicy`` backoff; the
replay cursor makes the re-read idempotent); non-retryable failures
propagate and the node relaunch path (``run_with_restarts`` / elastic
supervise) takes over — the successor seeds its cursor and resumes.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Sequence

from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.feed.columnar import ColumnAssembler, ColumnChunk
from tensorflowonspark_tpu.feed.datafeed import (
    ReplayCursor,
    columnize_rows,
    normalize_cursor_entry,
)
from tensorflowonspark_tpu.feed.manifest import (
    FileManifest,
    read_manifest,
    read_manifest_chunks,
    stream_id,
)
from tensorflowonspark_tpu.obs import flightrec
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils.failpoints import FailpointError, failpoint
from tensorflowonspark_tpu.utils.retry import DEFAULT_RETRYABLE, RetryPolicy

logger = logging.getLogger(__name__)

__all__ = ["IngestFeed", "RowPiece", "ShardReader", "metrics", "stream_id"]

# Read faults a shard read retries in place. FailpointError is included
# deliberately: the ``ingest.open_shard`` / ``ingest.read_block`` chaos
# sites exercise exactly this loop (docs/ROBUSTNESS.md failpoint
# conventions — a site opts into retrying injected faults).
_RETRYABLE = DEFAULT_RETRYABLE + (FailpointError,)


# -- obs ---------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: dict[str, Any] | None = None


def metrics() -> dict[str, Any]:
    """Pull-plane ingest counters in the process-global obs registry:
    shard files opened, column-payload bytes and records delivered by
    THIS node's executor-local readers. The driver-side
    ``MetricsAggregator`` differentiates ``feed_ingest_bytes_total``
    between scrapes into the per-node ``cluster_node_ingest_bytes_per_s``
    gauge — the scaling bench's "per-node throughput flat" criterion,
    readable straight off the registry."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from tensorflowonspark_tpu.obs.registry import default_registry

                r = default_registry()
                _metrics = {
                    "files": r.counter(
                        "feed_ingest_files_total",
                        "shard files opened by executor-local readers, "
                        "by format",
                    ),
                    "bytes": r.counter(
                        "feed_ingest_bytes_total",
                        "column-payload bytes ingested by executor-local "
                        "readers",
                    ),
                    "records": r.counter(
                        "feed_ingest_records_total",
                        "records ingested by executor-local readers",
                    ),
                    # live shard redistribution (handover protocol)
                    "plan_epoch": r.gauge(
                        "ingest_plan_epoch",
                        "membership epoch of the ingest plan currently "
                        "consumed (node) / published (driver)",
                    ),
                    "handover_s": r.histogram(
                        "ingest_handover_seconds",
                        "wall seconds from handover drain to re-split "
                        "adoption",
                    ),
                    "cursor_publishes": r.counter(
                        "ingest_cursor_publishes_total",
                        "replay-cursor publications to the driver KV, "
                        "by kind",
                    ),
                    "cursor_publish_s": r.histogram(
                        "ingest_cursor_publish_seconds",
                        "wall seconds per replay-cursor publication "
                        "(the autotune publish_blocks overhead signal)",
                    ),
                    # growing-dataset wire (TFCluster.extend_shards)
                    "growth_adoptions": r.counter(
                        "ingest_growth_adoptions_total",
                        "same-epoch plan-generation bumps adopted by a "
                        "lingering consumer (appended shards absorbed "
                        "without a membership bump)",
                    ),
                }
    return _metrics


# -- stream identity ---------------------------------------------------------
# stream_id now lives in feed/manifest.py (the driver's shard
# re-planner needs it without importing this module); re-exported here
# unchanged — a pure function of WHAT is read, which is what lets a
# seeded ReplayCursor recognize the already-consumed prefix.


class RowPiece(list):
    """A row-list piece (the non-columnizable fallback) stamped with
    its ``(stream, seq)`` so the consumed-cursor bookkeeping survives
    the fallback path; slicing preserves the stamp (the assembler
    splits head pieces across batches)."""

    __slots__ = ("stream", "seq")

    def __init__(self, rows: Sequence[Any], stream: str | None = None, seq: int = 0):
        super().__init__(rows)
        self.stream = stream
        self.seq = seq

    def __getitem__(self, i):
        out = super().__getitem__(i)
        if isinstance(i, slice):
            return RowPiece(out, self.stream, self.seq)
        return out


# -- executor-local reading --------------------------------------------------


class ShardReader:
    """Reads one node's shard — a list of manifests — locally, yielding
    stamped pieces (``ColumnChunk`` views / :class:`RowPiece` lists).

    Manifests are read sequentially (ordering is part of the replay
    contract); each manifest is one replay stream whose blocks carry
    ordinal ``seq``. A transient failure (``_RETRYABLE``) mid-manifest
    restarts that manifest's read under the jittered ``retry`` policy —
    the caller's :class:`ReplayCursor` drops the re-read prefix, so a
    retry can neither duplicate nor skip records (the ``ingest.
    open_shard`` / ``ingest.read_block`` failpoints exercise this).
    """

    def __init__(
        self,
        manifests: Sequence[Any],
        reader: Callable[[Any], Iterator[Any]] | None = None,
        records_per_chunk: int = 1024,
        retry: RetryPolicy | None = None,
        frame_cache: Any | None = None,
    ):
        self.manifests = list(manifests)
        self.reader = reader
        self.records_per_chunk = int(records_per_chunk)
        # Optional cachetier.FrameCache: 'columnar' manifests fetch
        # frame payloads through the shared read-through tier (one
        # backing read per frame across N co-located readers); cache
        # failure falls back to the local mmap — never an error.
        self.frame_cache = frame_cache
        self.retry = (
            retry
            if retry is not None
            else RetryPolicy(max_attempts=3, deadline_s=120.0)
        )

    def pieces(self, cursor: ReplayCursor) -> Iterator[Any]:
        """All pieces of this shard, in manifest order, deduped/ordered
        through ``cursor``."""
        for m in self.manifests:
            yield from self._manifest_pieces(m, cursor)

    def _manifest_pieces(self, m: Any, cursor: ReplayCursor) -> Iterator[Any]:
        # Hand-rolled rather than RetryPolicy.call: the body is a
        # GENERATOR (pieces stream out between faults), which a
        # callable-wrapping retry cannot express. The policy's
        # invariants are preserved: its jittered schedule, its counter,
        # and its deadline — a sleep never starts at or past the
        # deadline, and never overshoots it.
        from tensorflowonspark_tpu.utils.retry import _retry_counter

        delays = self.retry.delays()
        deadline = (
            None
            if self.retry.deadline_s is None
            else time.monotonic() + self.retry.deadline_s
        )
        attempt = 0
        while True:
            attempt += 1
            try:
                yield from self._read_once(m, cursor)
                return
            except _RETRYABLE as e:
                delay = next(delays, None)
                if delay is None:
                    raise
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise
                    delay = min(delay, remaining)
                _retry_counter().inc(site="ingest.shard")
                logger.warning(
                    "ingest: shard %s read failed (%s: %s); retrying "
                    "(attempt %d/%d) — the replay cursor drops re-read "
                    "blocks",
                    getattr(m, "path", m),
                    type(e).__name__,
                    e,
                    attempt,
                    self.retry.max_attempts,
                )
                time.sleep(delay)

    def _raw_pieces(self, m: Any) -> Iterator[Any]:
        if (
            self.reader is None
            and isinstance(m, FileManifest)
            and m.format == "columnar"
        ):
            # the on-disk wire format: zero-copy views over one mmap,
            # payload-CRC-verified per frame
            yield from read_manifest_chunks(
                m, frame_cache=self.frame_cache
            )
            return
        from tensorflowonspark_tpu.data.readers import columnar_pieces

        yield from columnar_pieces(
            read_manifest(m, self.reader), self.records_per_chunk
        )

    def _read_once(self, m: Any, cursor: ReplayCursor) -> Iterator[Any]:
        met = metrics()
        sid = stream_id(m)
        fmt = m.format if isinstance(m, FileManifest) else "custom"
        failpoint("ingest.open_shard")
        met["files"].inc(format=fmt)
        # ingest.read is an externally-measured interval (spans.record's
        # synthetic lane), accumulated around the read steps only: a
        # call-stack span held open across yields would swallow the
        # consumer's compute between pulls into "read" time.
        read_s = 0.0
        n_records = 0
        raw = self._raw_pieces(m)
        seq = -1
        try:
            while True:
                t0 = time.perf_counter()
                piece = next(raw, None)
                read_s += time.perf_counter() - t0
                if piece is None:
                    return
                seq += 1
                if failpoint("ingest.read_block") == "drop":
                    # chaos: block lost mid-shard — the cursor's gap
                    # check on the NEXT block surfaces it loudly
                    continue
                if not cursor.check(sid, seq):
                    continue  # replayed duplicate (retry/restart/re-plan)
                if isinstance(piece, ColumnChunk):
                    piece = ColumnChunk(
                        piece.kind,
                        piece.keys,
                        piece.arrays,
                        qname=piece.qname,
                        stream=sid,
                        seq=seq,
                    )
                    met["bytes"].inc(piece.nbytes)
                else:
                    piece = RowPiece(piece, sid, seq)
                met["records"].inc(len(piece))
                n_records += len(piece)
                yield piece
                # no piece reference held across the next read — the
                # same liveness rule as the wire pull loops (mmap
                # pinning is milder than ring slots, but uniform rules
                # are checkable rules)
                piece = None
        finally:
            try:
                obs_spans.record(
                    "ingest.read",
                    read_s,
                    path=str(getattr(m, "path", m)),
                    format=fmt,
                    records=n_records,
                )
            except Exception:  # pragma: no cover - interpreter teardown
                pass  # an abandoned reader GC'd at exit must stay quiet


# -- the DataFeed-shaped consumer --------------------------------------------


class IngestFeed:
    """The pull plane's in-node consumer: ``DataFeed``'s surface
    (``next_batch`` / ``should_stop`` / ``batch_stream`` / ``cursor`` /
    ``seed_cursor`` / ``terminate``) over an executor-local
    :class:`ShardReader` — no queue, no driver, no bytes over the
    control plane.

    Construct directly from manifests, or via ``ctx.get_ingest_feed()``
    which fetches this node's shard from the driver-published plan
    (``TFCluster.assign_shards``). With an ``input_mapping`` batches
    are ``{tensor: ndarray}`` columns SLICED from the shard's chunks
    (zero-copy within one chunk); without one, plain record lists.
    Like ``ManifestFeed``, batches fill across file boundaries — steady
    jit shapes are the point of the plane.
    """

    def __init__(
        self,
        manifests: Sequence[Any],
        input_mapping: dict[str, str] | None = None,
        reader: Callable[[Any], Iterator[Any]] | None = None,
        records_per_chunk: int = 1024,
        retry: RetryPolicy | None = None,
        plan_epoch: int = 0,
        plan_seq: int = 0,
        worker_index: int | None = None,
        plan_fetch: Callable[[int, float], dict | None] | None = None,
        cursor_publish: Callable[[dict], None] | None = None,
        epoch_watch: Callable[[], int] | None = None,
        publish_blocks: int = 32,
        adopt_timeout: float = 120.0,
        knob_fetch: Callable[[], dict | None] | None = None,
        frame_cache: Any | None = None,
    ):
        """``plan_fetch`` / ``cursor_publish`` / ``epoch_watch`` arm the
        live-shard-redistribution protocol (all three together — wired
        by ``ctx.get_ingest_feed`` when the driver published the plan
        with ``handover`` set): the feed watches the membership epoch
        (``epoch_watch``, one int read per block), publishes its
        record-exact replay cursor every ``publish_blocks`` fully
        consumed blocks — the crash-handover duplicate bound — and on
        an epoch bump drains to a block boundary, publishes, and adopts
        the driver's re-split (``plan_fetch(min_epoch, timeout)``,
        bounded by ``adopt_timeout``). Unarmed (the default), behavior
        is exactly the PR-8 static-shard feed."""
        self.input_mapping = input_mapping
        self.plan_epoch = int(plan_epoch)
        # plan GENERATION within the membership epoch (the growing-
        # dataset wire): TFCluster.extend_shards bumps it; the
        # exhaustion-linger adopts a same-epoch plan with a higher seq
        # as appended work instead of completing
        self.plan_seq = int(plan_seq)
        self.worker_index = worker_index
        self._user_reader = reader
        self._records_per_chunk = int(records_per_chunk)
        self._retry = retry
        self._frame_cache = frame_cache
        self._reader = ShardReader(
            manifests,
            reader=reader,
            records_per_chunk=records_per_chunk,
            retry=retry,
            frame_cache=frame_cache,
        )
        from tensorflowonspark_tpu.feed.datafeed import _replay_counter

        self._seq = ReplayCursor(
            name=f"ingest shard (worker "
            f"{worker_index if worker_index is not None else '?'})",
            on_drop=lambda _s: _replay_counter().inc(queue="ingest"),
        )
        self._assembler = (
            ColumnAssembler(input_mapping) if input_mapping else None
        )
        self._buffer: list[Any] = []  # rows of a partially-consumed piece
        self._iter: Iterator[Any] | None = None
        self._exhausted = False
        # Exactly-once bookkeeping. Pieces enter assembly in FIFO order
        # and records leave it in the same order, so one cumulative
        # consumption count maps back to (fully-consumed blocks, record
        # offset into the in-progress block) — the record-exact cursor.
        # cursor() runs on the training/checkpoint thread while the
        # DevicePrefetcher producer thread advances consumption, so the
        # bookkeeping is lock-guarded (tfsan dogfood; a torn deque/dict
        # read here would checkpoint a cursor with holes).
        self._cursor_lock = threading.Lock()
        self._delivered: deque = deque()  # (stream, seq, length, base)  # guarded-by: self._cursor_lock
        self._head_consumed = 0  # records consumed from _delivered[0]  # guarded-by: self._cursor_lock
        # stream -> consumed state: int (last fully consumed seq) or
        # [seq, skip] (seeded mid-block state not yet superseded by
        # this feed's own progress)
        self._done: dict[str, Any] = {}  # guarded-by: self._cursor_lock
        self._pending_skip: dict[str, tuple[int, int]] = {}  # seeded offsets  # guarded-by: self._cursor_lock
        # -- live shard redistribution (handover protocol) -----------------
        self._plan_fetch = plan_fetch
        self._cursor_publish = cursor_publish
        self._epoch_watch = epoch_watch
        self._handover = (
            plan_fetch is not None
            and epoch_watch is not None
        )
        self._publish_blocks = max(1, int(publish_blocks))  # guarded-by: self._cursor_lock
        self._adopt_timeout = float(adopt_timeout)
        self._blocks_since_publish = 0  # guarded-by: self._cursor_lock
        # Driver-pushed feed knobs (autotune): a driver-side controller
        # re-publishes {seq, knobs} to the KV; this feed polls at block
        # boundaries (time-gated) and adopts monotonically by seq.
        self._knob_fetch = knob_fetch
        self._knob_seq = -1  # last adopted knob publication seq
        self._knob_poll_ts = 0.0  # consumer-thread-only time gate
        self._terminated = False
        self._complete = False
        if self._handover:
            metrics()["plan_epoch"].set(self.plan_epoch)
            # announce the subscription: an epoch bump landing before
            # the first periodic publication must still find this
            # consumer in the driver's cursor table, so the drain wait
            # covers it (zero-dup needs the driver to wait for us)
            self._publish_cursor(final=False, kind="announce")

    # -- replay cursor -------------------------------------------------
    def cursor(self) -> dict[str, Any]:
        """Record-exact consumption snapshot, per stream: ``seq`` when
        block ``seq`` is the last FULLY consumed one, or ``[seq, skip]``
        when additionally the first ``skip`` records of block
        ``seq + 1`` have left in batches. Records still buffered inside
        the feed (read but never batched out) are NOT counted — a
        successor seeded with this snapshot (:meth:`seed_cursor`)
        re-reads them: zero duplicates, zero holes, mid-shard and even
        mid-block. Checkpoint it beside the train state. Safe to call
        from any thread while the feed is being consumed."""
        with self._cursor_lock:
            return self._cursor_locked()

    def _cursor_locked(self) -> dict[str, Any]:  # lint: holds-lock
        out: dict[str, Any] = dict(self._done)
        if self._delivered and self._head_consumed:
            s, q, _ln, base = self._delivered[0]
            if s is not None:
                out[s] = wire.encode_cursor_entry(
                    q - 1, base + self._head_consumed
                )
        return out

    def seed_cursor(self, cursor: dict[str, Any]) -> None:
        """Adopt a :meth:`cursor` snapshot BEFORE consuming. Whole
        blocks at or below each stream's seeded seq drop as replayed
        duplicates on the re-read; a ``[seq, skip]`` entry additionally
        trims the first ``skip`` records off block ``seq + 1``. Plain
        ``{stream: seq}`` cursors (the push plane's ``DataFeed``
        format) are accepted unchanged.

        Seeded state is itself part of :meth:`cursor`'s output until
        this feed makes further progress on the stream: a successor
        that crashes before touching an already-consumed stream must
        still hand ITS successor the full consumed prefix — otherwise
        the third incarnation would replay whole streams (duplicates).
        """
        seed: dict[str, int] = {}
        with self._cursor_lock:
            for s, v in cursor.items():
                s = str(s)
                seq0, skip = normalize_cursor_entry(v)
                if seq0 >= 0:
                    seed[s] = seq0
                if skip > 0:
                    self._pending_skip[s] = (seq0 + 1, skip)
                    self._done[s] = wire.encode_cursor_entry(seq0, skip)
                elif seq0 >= 0:
                    self._done[s] = wire.encode_cursor_entry(seq0)
        self._seq.seed(seed)

    # -- live shard redistribution (the handover protocol) --------------
    def _handover_due(self) -> bool:
        """One int compare per block: has the membership epoch moved
        past the plan this feed is consuming?"""
        return self._handover and self._epoch_watch() > self.plan_epoch

    def publish_cursor(self, final: bool = False) -> None:
        """Publish this feed's record-exact replay cursor to the driver
        KV now (best-effort, like the periodic beat). A planned leaver
        calls this right before exiting so the re-split starts from an
        exact cursor — zero duplicates — instead of the last periodic
        one."""
        self._publish_cursor(final=final, kind="explicit")

    def _publish_cursor(
        self,
        epoch: int | None = None,
        final: bool = False,
        kind: str = "periodic",
        done: bool | None = None,
    ) -> None:
        """Best-effort by contract: a lost publication can only widen
        the crash-handover duplicate window (the driver falls back to
        an older cursor), never lose records — so a failure here warns
        and moves on rather than killing training.

        Default stamp is ``plan_epoch`` — the plan this cursor was
        consumed UNDER — never the watched epoch: a periodic beat that
        landed after a bump but before this feed drained must not
        satisfy the driver's drain wait (it would release the re-split
        while this consumer is still emitting old-plan records). Only
        the drain/final paths, which have actually stopped consuming,
        pass the observed epoch explicitly."""
        if self._cursor_publish is None:
            return
        if epoch is None:
            epoch = self.plan_epoch
        payload = wire.encode(
            "ingest.cursor_payload",
            epoch=int(epoch),
            final=bool(final),
            # done = this consumer will NEVER consume again (final OR
            # terminated): the driver stops waiting on it, stops
            # assigning it work, and completion need not require a
            # fresh stamp from it
            done=bool(final if done is None else done),
            cursor=self.cursor(),
            records_per_chunk=self._records_per_chunk,
            # block→record math hint for the driver's re-planner: a
            # custom reader streams records_per_chunk blocks even over
            # 'columnar'-format manifests
            frame_blocks=False if self._user_reader is not None else None,
            # plan generation this cursor was consumed under: the
            # driver's completion check must not accept a final
            # published BEFORE the dataset grew (growing-dataset wire)
            plan_seq=self.plan_seq,
        )
        try:
            t0 = time.perf_counter()
            self._cursor_publish(payload)
            met = metrics()
            met["cursor_publishes"].inc(kind=kind)
            # measured per-publication cost: the autotune
            # publish_blocks policy trades this overhead against the
            # crash-replay duplicate bound
            met["cursor_publish_s"].observe(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 - best-effort by contract
            logger.warning(
                "ingest: cursor publication failed (%s) — the driver "
                "will fall back to the last one it has (duplicates "
                "bounded by the staleness, zero-gap unaffected)",
                e,
            )

    def _run_handover(self) -> None:
        """Cooperative adoption, the consumer side of the protocol:
        (1) drain to a block boundary on the old plan — every record
        that left in a batch is consumed; read-but-unconsumed records
        buffered in the feed are DISCARDED for replay (the re-split
        covers them, so discarding is what makes the handover
        zero-dup/zero-gap); (2) publish the record-exact ``[seq,
        skip]`` cursor; (3) adopt the driver's re-split for the new
        epoch, reseeding the sequence cursor from consumed state."""
        t0 = time.monotonic()
        observed = max(self._epoch_watch(), self.plan_epoch)
        skip_publish = failpoint("ingest.handover_drain") == "drop"
        with self._cursor_lock:
            # fold the consumed snapshot (incl. the partial head's
            # [seq, skip]) into _done, then drop everything unconsumed
            self._done = self._cursor_locked()
            self._delivered.clear()
            self._head_consumed = 0
            self._pending_skip.clear()
        self._buffer = []
        if self._assembler is not None and len(self._assembler):
            self._assembler.take(len(self._assembler))  # discard: replays
        it, self._iter = self._iter, None
        if it is not None and hasattr(it, "close"):
            it.close()
        if not skip_publish:
            self._publish_cursor(epoch=observed, final=False, kind="drain")
        failpoint("ingest.plan_adopt")
        plan = self._plan_fetch(observed, self._adopt_timeout)
        if plan is None:
            raise TimeoutError(
                f"ingest handover: no plan for membership epoch >= "
                f"{observed} within {self._adopt_timeout}s — the driver "
                "stopped republishing (worker "
                f"{self.worker_index if self.worker_index is not None else '?'})"
            )
        self._adopt(plan)
        dt = time.monotonic() - t0
        metrics()["handover_s"].observe(dt)
        flightrec.note(
            "ingest_handover",
            worker=self.worker_index,
            from_epoch=observed,
            epoch=self.plan_epoch,
            manifests=len(self._reader.manifests),
            seconds=round(dt, 3),
        )
        logger.info(
            "ingest: handover to plan epoch %d (%d manifest(s), %.3fs)",
            self.plan_epoch,
            len(self._reader.manifests),
            dt,
        )

    def _adopt(self, plan: dict) -> None:
        """Swap in a re-split plan: fresh reader, fresh block-sequence
        cursor reseeded from consumed state (a zero-consumption stream
        keeps its id across a re-split, and the old cursor's accepted
        blocks would wrongly dedupe its legitimate re-read)."""
        manifests = list(plan.get("manifests") or [])
        from tensorflowonspark_tpu.feed.datafeed import _replay_counter

        with self._cursor_lock:
            self.plan_epoch = int(plan.get("epoch", self.plan_epoch))
            self.plan_seq = int(plan.get("seq") or 0)
            self._complete = bool(plan.get("complete"))
            self._pending_skip = {}
            done = dict(self._done)
        self._seq = ReplayCursor(
            name=f"ingest shard (worker "
            f"{self.worker_index if self.worker_index is not None else '?'})",
            on_drop=lambda _s: _replay_counter().inc(queue="ingest"),
        )
        # re-seed from consumed state through the ONE entry-splitting
        # implementation (seed_cursor re-derives _done from its own
        # snapshot — idempotent)
        self.seed_cursor(done)
        self._reader = ShardReader(
            manifests,
            reader=self._user_reader,
            records_per_chunk=self._records_per_chunk,
            retry=self._retry,
            frame_cache=self._frame_cache,
        )
        self._iter = None
        self._exhausted = False
        metrics()["plan_epoch"].set(self.plan_epoch)

    def _adopt_growth(self, plan: dict) -> None:
        """Adopt a same-epoch plan-generation bump from the linger: the
        plan's manifest list is CUMULATIVE (old shard + appended), but
        at linger time every current stream is fully consumed — so the
        reader is rebuilt over only the streams ``_done`` has no state
        for (the appended ones), avoiding an O(history) re-scan per
        growth cycle. ``_done`` keeps the full consumed prefix, so
        ``cursor()`` still reports exactly-once state over the whole
        grown dataset."""
        with self._cursor_lock:
            consumed = set(self._done)
        manifests = [
            m
            for m in (plan.get("manifests") or [])
            if stream_id(m) not in consumed
        ]
        n_appended = len(manifests)
        self._adopt(dict(plan, manifests=manifests))
        metrics()["growth_adoptions"].inc()
        flightrec.note(
            "ingest_handover",
            worker=self.worker_index,
            cause="growth",
            epoch=self.plan_epoch,
            plan_seq=self.plan_seq,
            manifests=n_appended,
        )
        logger.info(
            "ingest: adopted grown plan seq %d (%d appended "
            "manifest(s) at epoch %d)",
            self.plan_seq,
            n_appended,
            self.plan_epoch,
        )

    def _await_redistribution(self) -> bool:
        """Shard exhausted under an armed handover: publish the FINAL
        cursor (full consumption, the driver's completion signal) and
        linger for either a plan-epoch bump — adopt the re-split and
        return True (more work may exist) — or the driver's completion
        marker / :meth:`terminate` — return False, the feed is done.
        The linger is what lets a survivor that finished its own shard
        early absorb a dead peer's remainder instead of exiting."""
        if not self._handover or self._terminated or self._complete:
            return False
        published_final = False
        while True:
            if self._terminated:
                return False
            if self._handover_due():
                self._run_handover()
                return not self._complete
            if not published_final:
                # Published only while NO bump is pending, stamped with
                # the PLAN epoch: finality at epoch E means "I adopted
                # plan E and consumed all of it". Stamping the watched
                # epoch here would let a final slip out between a bump
                # and this consumer's adoption — the driver's
                # completion check would then release everyone while
                # the re-split's manifests are still unread (a
                # zero-gap race).
                self._publish_cursor(
                    epoch=self.plan_epoch, final=True, kind="final"
                )
                published_final = True
            plan = self._plan_fetch(self.plan_epoch, 0.0)
            if (
                plan is not None
                and plan.get("complete")
                and int(plan.get("epoch", 0)) >= self.plan_epoch
            ):
                self._complete = True
                return False
            if (
                plan is not None
                and not plan.get("complete")
                and int(plan.get("epoch", 0)) == self.plan_epoch
                and int(plan.get("seq") or 0) > self.plan_seq
            ):
                # the growing-dataset wire: a SAME-epoch plan with a
                # higher generation is appended work (TFCluster.
                # extend_shards) — adopt it and resume consuming. The
                # final published above is stamped with the OLD seq, so
                # the driver's completion check cannot mistake it for
                # exhaustion of the grown dataset.
                self._adopt_growth(plan)
                return True
            time.sleep(0.25)

    # -- iteration core ------------------------------------------------
    def _pieces_iter(self) -> Iterator[Any]:
        if self._iter is None:
            self._iter = self._reader.pieces(self._seq)
        return self._iter

    def _pull_piece(self, inline_handover: bool = True) -> Any | None:
        """Next piece off the reader, seeded-skip applied and delivery
        recorded for the consumed-cursor bookkeeping.

        With the handover armed, an epoch bump observed here either
        runs the handover INLINE (default — safe whenever every
        read-but-unconsumed record lives in feed-owned buffers, which
        the drain discards for replay) or, with
        ``inline_handover=False``, returns ``None`` as a PAUSE so the
        caller can release externally buffered rows first (the
        mapping-less ``batch_stream``, whose pending rows sit inside
        ``fixed_size_batches``)."""
        while not self._exhausted:
            if self._handover_due():
                if not inline_handover:
                    return None  # pause: caller drains, then hands over
                self._run_handover()
                continue
            piece = next(self._pieces_iter(), None)
            if piece is None:
                self._exhausted = True
                return None
            stream = getattr(piece, "stream", None)
            seq = int(getattr(piece, "seq", 0))
            base = 0
            if stream is not None:
                with self._cursor_lock:
                    sk = self._pending_skip.get(stream)
                    matched = sk is not None and sk[0] == seq
                    if matched:
                        del self._pending_skip[stream]
                if matched:
                    base = min(int(sk[1]), len(piece))
                    if base:
                        piece = (
                            piece.view(base, len(piece))
                            if isinstance(piece, ColumnChunk)
                            else RowPiece(list(piece)[base:], stream, seq)
                        )
            if len(piece):
                with self._cursor_lock:
                    self._delivered.append((stream, seq, len(piece), base))
                return piece
        return None

    def _advance_consumed(self, n: int) -> None:
        """Records left the feed in a batch (or were dropped at the
        tail): pop fully-consumed pieces off the delivery FIFO and
        advance the per-stream done cursor. Every ``publish_blocks``
        fully consumed blocks, the handover-armed feed publishes its
        cursor to the driver KV — the periodic beat whose interval
        bounds crash-handover duplicates."""
        publish = False
        with self._cursor_lock:
            self._head_consumed += int(n)
            while self._delivered:
                s, q, ln, _base = self._delivered[0]
                if self._head_consumed < ln:
                    break
                self._delivered.popleft()
                self._head_consumed -= ln
                if s is not None:
                    self._done[s] = q
                    self._blocks_since_publish += 1
            if (
                self._handover
                and self._blocks_since_publish >= self._publish_blocks
            ):
                self._blocks_since_publish = 0
                publish = True
        if publish:
            self._publish_cursor(final=False, kind="periodic")
        self._maybe_adopt_knobs()

    def set_publish_blocks(self, blocks: int) -> int:
        """Live-set the cursor-publication interval (the autotune
        actuation path for the ``ingest.publish_blocks`` knob): how
        many fully consumed blocks between periodic replay-cursor
        publications — the knob trading publication RPC overhead
        against the crash-handover duplicate bound. Returns the value
        in effect."""
        blocks = max(1, int(blocks))
        with self._cursor_lock:
            self._publish_blocks = blocks
        return blocks

    def publish_blocks(self) -> int:
        """The cursor-publication interval in effect (knob readback)."""
        with self._cursor_lock:
            return self._publish_blocks

    def _maybe_adopt_knobs(self, now: float | None = None) -> None:
        """Consumer thread, outside the cursor lock: poll the driver's
        feed-knob publication (time-gated — at most one KV read every
        few seconds regardless of batch rate) and adopt it
        monotonically by seq. Best-effort like the cursor beat: a
        failed fetch warns once per poll and keeps the current knobs."""
        if self._knob_fetch is None:
            return
        if now is None:
            now = time.monotonic()
        if now - self._knob_poll_ts < 5.0:
            return
        self._knob_poll_ts = now
        try:
            pub = self._knob_fetch()
        except Exception as e:  # noqa: BLE001 - best-effort by contract
            logger.warning(
                "ingest: feed-knob fetch failed (%s) — keeping the "
                "current knobs",
                e,
            )
            return
        if not pub:
            return
        seq = int(pub.get("seq", 0))
        if seq <= self._knob_seq:
            return  # already adopted (or a stale republish)
        self._knob_seq = seq
        knobs = pub.get("knobs") or {}
        if "publish_blocks" in knobs:
            self.set_publish_blocks(int(knobs["publish_blocks"]))
            logger.info(
                "ingest: adopted driver feed knobs seq=%d "
                "(publish_blocks=%d)",
                seq,
                self.publish_blocks(),
            )

    def should_stop(self) -> bool:
        """True once the shard is exhausted AND every buffered record
        has left in a batch (``DataFeed.should_stop`` contract).

        Handover-armed feeds add one clause: an exhausted-and-drained
        feed is not DONE until the driver says the whole dataset is
        (completion marker) or an epoch bump hands it more work — so
        this call may BLOCK while it lingers (bounded by driver
        progress; ``terminate()`` from another thread unblocks it)."""
        drained = (
            self._exhausted
            and not self._buffer
            and (self._assembler is None or len(self._assembler) == 0)
        )
        if not drained:
            return False
        if not self._handover or self._terminated or self._complete:
            return True
        return not self._await_redistribution()

    def next_batch(self, batch_size: int) -> list | dict[str, Any]:
        """Up to ``batch_size`` records; partial only at shard end.
        Mapped feeds return sliced ``{tensor: column}`` dicts, mapping-
        less feeds record lists (``ColumnChunk.rows`` semantics, as on
        the push wire)."""
        if self._assembler is None:
            if self.input_mapping is not None:
                # degenerate empty mapping: legacy stacking contract
                return columnize_rows(
                    self._next_raw(batch_size), self.input_mapping
                )
            return self._next_raw(batch_size)
        asm = self._assembler
        while len(asm) < batch_size:
            piece = self._pull_piece()
            if piece is None:
                break
            asm.push(piece)
        n = min(batch_size, len(asm))
        out = asm.take(batch_size)
        self._advance_consumed(n)
        return out

    def _next_raw(
        self,
        batch_size: int,
        account: bool = True,
        inline_handover: bool = True,
    ) -> list:
        """Up to ``batch_size`` raw records. ``account=False`` defers
        the consumed-cursor advance to the caller — rows handed to an
        intermediate buffer (``fixed_size_batches``) have NOT left the
        feed yet, and counting them consumed would punch resume holes.

        An inline handover is only legal while every pulled row is in
        FEED-OWNED buffers (the drain discards those for replay); rows
        already moved into the local ``batch`` are neither claimed by
        the drain cursor nor discarded, so once ``batch`` is non-empty
        an epoch bump PAUSES the loop instead (partial batch out,
        consumption accounted against the old plan; the handover runs
        on the next call, when the slate is clean)."""
        batch: list[Any] = []
        while len(batch) < batch_size:
            take = batch_size - len(batch)
            if self._buffer:
                batch.extend(self._buffer[:take])
                del self._buffer[:take]
                continue
            piece = self._pull_piece(
                inline_handover=inline_handover and not batch
            )
            if piece is None:
                break
            if isinstance(piece, ColumnChunk):
                self._buffer.extend(piece.rows())
            else:
                self._buffer.extend(piece)
            piece = None
        if account:
            self._advance_consumed(len(batch))
        return batch

    def batch_stream(
        self,
        batch_size: int,
        multiple_of: int = 1,
        input_mapping: dict[str, str] | None = None,
    ):
        """Fixed-size batches with the ``DataFeed.batch_stream``
        contract: every yield has exactly ``batch_size`` records
        (rounded down to ``multiple_of``) until the shard tail, which
        trims to the largest multiple (sub-multiple remainder dropped
        with a log line). The mapping may come from the constructor
        (``DataFeed`` style) or here (``ManifestFeed`` style) — either
        way ``DevicePrefetcher.from_feed`` drives it unchanged."""
        mapping = (
            input_mapping if input_mapping is not None else self.input_mapping
        )
        if not mapping:
            from tensorflowonspark_tpu.utils.batching import fixed_size_batches

            # consumption is advanced per EMITTED batch, never when rows
            # merely enter fixed_size_batches' pending buffer — those
            # rows have not left the feed, and counting them consumed
            # would make a checkpointed cursor skip them on resume.
            # Handover pauses must happen OUTSIDE _pull_piece here
            # (inline_handover=False): rows pending inside
            # fixed_size_batches are out of the feed's reach, so the
            # drain first lets the batcher flush its trimmed tail, then
            # hands over — the un-emitted sub-multiple remainder stays
            # unconsumed and replays under the re-split.
            while True:
                pulled = 0
                paused = [False]

                def records():
                    nonlocal pulled
                    while True:
                        if self._handover_due():
                            paused[0] = True
                            return
                        rows = self._next_raw(
                            batch_size, account=False, inline_handover=False
                        )
                        if not rows:
                            paused[0] = self._handover_due()
                            return
                        pulled += len(rows)
                        yield from rows

                emitted = 0
                for batch in fixed_size_batches(
                    records(),
                    batch_size,
                    multiple_of,
                    assemble=lambda rows: list(rows),
                ):
                    emitted += len(batch)
                    self._advance_consumed(len(batch))
                    yield batch
                if paused[0]:
                    # the pulled-but-unemitted remainder was NOT
                    # advanced: the handover discards it for replay
                    self._run_handover()
                    continue
                # normal exhaustion: the sub-multiple remainder was
                # DROPPED (drop-remainder semantics) — dropped counts
                # as consumed. Unreached on an early generator close,
                # where the pending rows were never delivered and must
                # replay.
                self._advance_consumed(pulled - emitted)
                if (
                    self._exhausted
                    and self._handover
                    and not self._terminated
                    and not self._complete
                    and self._await_redistribution()
                ):
                    continue
                return
        if self._assembler is None or self._assembler.mapping != mapping:
            old = self._assembler
            self._assembler = ColumnAssembler(dict(mapping))
            # FIFO order is the cursor's correctness invariant: oldest
            # unconsumed records (a prior mapping-less next_batch's row
            # buffer) re-enter assembly first.
            if self._buffer:
                self._assembler.push(list(self._buffer))
                self._buffer = []
            if old is not None:
                for piece in old.drain_pieces():
                    self._assembler.push(piece)
        bs = batch_size - batch_size % multiple_of
        if bs == 0:
            raise ValueError(
                f"batch_size < multiple_of ({multiple_of}); nothing to yield"
            )
        asm = self._assembler
        while True:
            while len(asm) < bs:
                piece = self._pull_piece()
                if piece is None:
                    break
                asm.push(piece)
            if len(asm) >= bs:
                batch = asm.take(bs)
                self._advance_consumed(bs)
                yield batch
                continue
            # reader exhausted (handover pauses run inline on this
            # path — every buffered record is feed-owned)
            if (
                self._handover
                and not self._terminated
                and not self._complete
            ):
                # plan boundary: flush the buffered tail exactly like
                # the feed end (one short batch + drop-remainder), so
                # the FINAL cursor the await publishes is exact, then
                # linger for a re-split or the completion marker
                yield from self._flush_tail(asm, multiple_of)
                if self._await_redistribution():
                    continue
            break
        yield from self._flush_tail(asm, multiple_of)

    def _flush_tail(self, asm: ColumnAssembler, multiple_of: int):
        """Feed-end tail contract, shared by final exhaustion and every
        handover plan boundary: emit the largest ``multiple_of``
        multiple as one (short) batch, drop the sub-multiple remainder
        loudly — dropped counts as consumed (a resume or re-split must
        not replay it; same semantics as the push wire)."""
        tail = len(asm) - len(asm) % multiple_of
        rem = len(asm) % multiple_of
        if rem:
            logger.warning(
                "dropping %d tail records (not a multiple of %d)",
                rem,
                multiple_of,
            )
        if tail:
            batch = asm.take(tail)
            self._advance_consumed(tail)
            yield batch
        if len(asm):
            asm.take(len(asm))
            self._advance_consumed(rem)

    def terminate(self) -> None:
        """Stop reading (early stop). Purely local — there is no
        producer to signal on the pull plane — except that a
        handover-armed feed publishes its cursor once more (best
        effort) so the driver's view of this consumer is as fresh as
        possible, and any blocked :meth:`should_stop` linger unblocks."""
        self._terminated = True
        self._exhausted = True
        it, self._iter = self._iter, None
        if it is not None and hasattr(it, "close"):
            it.close()
        if self._handover:
            # a terminated feed consumes nothing more, so its cursor is
            # drain-exact: stamp the observed epoch, sparing the driver
            # a full drain-timeout wait on a consumer that cannot answer
            self._publish_cursor(
                epoch=max(self.plan_epoch, self._epoch_watch()),
                final=False,
                kind="terminate",
                done=True,
            )
