"""The data planes: host-side queues (push) and executor-local sharded
readers (pull) into the training loop.

Reference parity: the ``DataFeed`` class of ``tensorflowonspark/TFNode.py``
plus the queue sentinels of ``marker.py``. ``DevicePrefetcher`` extends
the plane one hop further than the reference could: host batch ->
device, overlapped with the training step. ``IngestFeed`` restores the
reference's executor-local-feed property for ``InputMode.TENSORFLOW``:
the driver ships manifests, nodes read their own shards (``ingest.py``).
"""

from tensorflowonspark_tpu.feed.datafeed import DataFeed
from tensorflowonspark_tpu.feed.ingest import IngestFeed
from tensorflowonspark_tpu.feed.manifest import FileManifest, ManifestFeed
from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher

__all__ = [
    "DataFeed",
    "DevicePrefetcher",
    "FileManifest",
    "IngestFeed",
    "ManifestFeed",
]
