"""The push data plane: host-side queues into the training loop.

Reference parity: the ``DataFeed`` class of ``tensorflowonspark/TFNode.py``
plus the queue sentinels of ``marker.py``. ``DevicePrefetcher`` extends
the plane one hop further than the reference could: host batch ->
device, overlapped with the training step.
"""

from tensorflowonspark_tpu.feed.datafeed import DataFeed
from tensorflowonspark_tpu.feed.manifest import FileManifest, ManifestFeed
from tensorflowonspark_tpu.feed.prefetch import DevicePrefetcher

__all__ = ["DataFeed", "DevicePrefetcher", "FileManifest", "ManifestFeed"]
