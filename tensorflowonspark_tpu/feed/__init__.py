"""The push data plane: host-side queues into the training loop.

Reference parity: the ``DataFeed`` class of ``tensorflowonspark/TFNode.py``
plus the queue sentinels of ``marker.py``.
"""

from tensorflowonspark_tpu.feed.datafeed import DataFeed

__all__ = ["DataFeed"]
