"""``DataFeed`` — the in-graph consumer API for the push data plane.

Reference parity: ``tensorflowonspark/TFNode.py:DataFeed``
(``next_batch``, ``should_stop``, ``batch_results``, ``terminate``), plus
the sentinel semantics of ``marker.py``.

Queue protocol: each element on the input queue is either a
:class:`~tensorflowonspark_tpu.cluster.marker.Marker` or a *chunk* (a list
of records). Producers put chunks — not single records — so a remote
(proxied) put amortizes its round-trip over many records; this removes the
per-item pickle-proxy tax SURVEY.md §3.2 identifies as the reference's
dominant overhead.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
from typing import Any, Sequence

import numpy as np

from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.cluster.marker import EndOfFeed, EndPartition, Marker
from tensorflowonspark_tpu.feed.columnar import (
    ColumnAssembler,
    ColumnChunk,
    ColumnarFrame,
    column_batches,
    decode_frame,
)
from tensorflowonspark_tpu.obs import spans as obs_spans
from tensorflowonspark_tpu.utils.failpoints import failpoint

logger = logging.getLogger(__name__)

# Sentinel for a chunk discarded by the armed ``columnar.frame`` drop
# failpoint — or recognized as a replayed duplicate by the seq cursor:
# the pull loop skips it (the NEXT frame's sequence check is what
# surfaces a real loss).
_DROPPED = object()


def _replay_counter():
    from tensorflowonspark_tpu.obs.registry import default_registry

    return default_registry().counter(
        "feed_replay_skipped_total",
        "replayed duplicate frames dropped by the seq cursor, by queue",
    )

def normalize_cursor_entry(v: Any) -> tuple[int, int]:
    """Canonical ``(seq, skip)`` form of one replay-cursor entry — THE
    serialization both planes (and the driver's shard re-planner)
    agree on. An entry is either a plain int ``seq`` (block ``seq`` is
    the last fully-consumed one; the push plane's ``DataFeed.cursor``
    format) or a ``[seq, skip]`` pair (additionally the first ``skip``
    records of block ``seq + 1`` left in batches — the pull plane's
    record-exact mid-block form). Entries are JSON round-trip safe by
    construction: ints and two-int lists.

    The wire form itself is declared in ``cluster/wire.py`` (schema
    ``ingest.cursor_entry``); this is the feed-plane name for its
    decoder, kept because every consumer in both planes imports it
    from here."""
    return wire.decode_cursor_entry(v)


def cursor_covers(a: Any, b: Any) -> bool:
    """True when consumption claim ``a`` covers at least as many
    records as ``b`` (same stream). Claims are append-only truths —
    anything either side says was consumed, was — so merging two
    cursors for one stream keeps whichever covers more."""
    return normalize_cursor_entry(a) >= normalize_cursor_entry(b)


class ReplayCursor:
    """Per-stream frame/chunk sequence cursor — THE exactly-once and
    ordering primitive both data planes share.

    Producers stamp every columnar piece of one logical stream with a
    monotonic ``seq`` (the push wire's frame header, the pull plane's
    block ordinal). :meth:`check` resolves each arriving ``(stream,
    seq)`` into one of three verdicts: the expected seq advances the
    cursor (accept); a seq *behind* the cursor is a replayed duplicate
    — an elastic re-feed, a restarted executor-local reader, a
    retried shard read — and is dropped (``on_drop`` hook fires),
    giving exactly-once consumption through any replay; a seq *ahead*
    of the cursor means a piece was lost mid-stream and records
    silently vanished — raise instead of training on a hole.

    :meth:`snapshot`/:meth:`seed` make the cursor durable: a consumer
    checkpoints it beside its train state, and a successor (restart,
    relaunch, elastic rejoin) seeds a fresh cursor so the
    already-consumed prefix drops silently on replay.

    Thread-safety: :meth:`check` runs on whatever thread drives the
    pull loop (the ``DevicePrefetcher`` producer in the default train
    loop), while :meth:`snapshot` is called from the training/checkpoint
    thread — a cross-thread pair, so ``_state`` is lock-guarded
    (tfsan's dogfood pass; the witness validates the annotation in
    instrumented runs).
    """

    __slots__ = ("name", "_lock", "_state", "_on_drop")

    def __init__(self, name: str = "", on_drop=None):
        self.name = name
        self._lock = threading.Lock()
        self._state: dict[str, int] = {}  # guarded-by: self._lock
        self._on_drop = on_drop

    def check(self, stream: str | None, seq: int) -> bool:
        """True to accept, False to drop a replayed duplicate; raises
        RuntimeError on a forward gap (a lost piece)."""
        if stream is None:
            return True
        with self._lock:
            last = self._state.get(stream)
            expected = 0 if last is None else last + 1
            if seq == expected:
                self._state[stream] = seq
                return True
        if seq < expected:
            # on_drop (an obs counter bump) deliberately runs outside
            # the lock: no caller-owned locks are taken under _lock, so
            # the cursor can never participate in a lock-order cycle
            if self._on_drop is not None:
                self._on_drop(stream)
            return False
        raise RuntimeError(
            f"columnar frame sequence gap on {self.name or 'stream'} "
            f"stream {stream}: expected frame {expected}, got "
            f"{seq} — a frame was dropped mid-stream"
        )

    def snapshot(self) -> dict[str, int]:
        """Last accepted ``seq`` per live stream."""
        with self._lock:
            return dict(self._state)

    def seed(self, cursor: dict[str, Any]) -> None:
        """Adopt a snapshot: pieces at or below each stream's seeded
        seq are treated as replayed duplicates, not gaps. Entries may
        be plain ints or the pull plane's ``[seq, skip]`` form (see
        :func:`normalize_cursor_entry`); only the whole-block part
        seeds here — record-level trimming is the feed's job
        (``IngestFeed.seed_cursor``)."""
        with self._lock:
            for stream, entry in cursor.items():
                seq, _skip = normalize_cursor_entry(entry)
                if seq >= 0:
                    self._state[str(stream)] = seq

    def clear(self) -> None:
        with self._lock:
            self._state.clear()


class FeedTimeout(TimeoutError):
    """The input queue produced nothing for the whole feed-timeout
    window: the producer (driver feeder thread) stalled or died. Raised
    from the consumer pull loop instead of blocking forever — the
    consumer-side mirror of the driver's "timeout while feeding
    partition". Only armed when a policy exists (constructor value, or
    the KV ``TFCluster.train`` publishes): stream feeds are legitimately
    quiet for arbitrary stretches, so without a policy the pull blocks
    indefinitely, as before."""


def columnize_rows(
    batch: Sequence[Any], input_mapping: dict[str, str]
) -> dict[str, np.ndarray]:
    """Stack a list of row-records into {tensor_name: array} columns —
    THE column-assembly implementation (``api/pipeline.columnize``
    delegates here for its mapping path).

    Tuple/list records are read by *position* (mapping order = column
    order, the reference's contract), and the mapping must name every
    field — a subset would silently bind fields to the wrong tensors.
    Dict records are read by the mapping's field-name keys; a record
    missing a mapped field fails loudly — silently indexing dicts by
    position was the round-1 trap.
    """
    out: dict[str, np.ndarray] = {}
    if batch and isinstance(batch[0], dict):
        for field, tensor in input_mapping.items():
            try:
                out[tensor] = np.array([row[field] for row in batch])
            except (KeyError, TypeError) as e:
                raise KeyError(
                    f"input_mapping field {field!r} not present in a "
                    f"dict record (record keys: "
                    f"{sorted(batch[0])}); mapping={input_mapping}"
                ) from e
        return out
    if batch and isinstance(batch[0], (tuple, list)):
        cols = list(input_mapping)
        if len(batch[0]) != len(cols):
            raise ValueError(
                f"input_mapping has {len(cols)} columns {cols} but "
                f"records have {len(batch[0])} fields; for tuple "
                "records the mapping must name every field, in order"
            )
    for i, tensor in enumerate(input_mapping.values()):
        out[tensor] = np.array([row[i] for row in batch])
    return out


class DataFeed:
    """Pulls host-fed batches off the node's input queue; pushes inference
    results back on the output queue.

    Args mirror the reference: ``mgr`` is the node's manager handle,
    ``train_mode`` selects whether ``batch_results`` is expected,
    ``input_mapping`` (ordered dict of record-field → tensor name) makes
    ``next_batch`` return a dict of stacked columns instead of a flat list.
    """

    def __init__(
        self,
        mgr,
        train_mode: bool = True,
        qname_in: str = "input",
        qname_out: str = "output",
        input_mapping: dict[str, str] | None = None,
        feed_timeout: float | None = None,
        worker_index: int | None = None,
    ):
        self.mgr = mgr
        self.train_mode = train_mode
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.input_mapping = input_mapping
        # Pull-loop policy: explicit ctor value wins; otherwise resolved
        # lazily from the manager KV that TFCluster.train publishes at
        # feed start (re-probed until it appears — map_fun typically
        # constructs its DataFeed before the driver's first feed thread
        # has connected, and latching a fallback then would silently
        # discard the user's value). None = unbounded (stream feeds).
        self._feed_timeout = feed_timeout
        # Names this consumer in FeedTimeout messages (ctx.get_data_feed
        # passes the node's executor id).
        self.worker_index = worker_index
        # reference-parity public surface (TFNode.py DataFeed exposed it);
        # derived, not used internally
        self.input_tensors = (
            list(input_mapping.values()) if input_mapping is not None else None
        )
        self.done_feeding = False
        self._queue_in = mgr.get_queue(qname_in)
        self._queue_out = mgr.get_queue(qname_out)
        self._buffer: list[Any] = []  # records from a partially-consumed chunk
        # Columnar consumption state: pending pieces (ColumnChunk views /
        # row lists) assembled by SLICING when an input_mapping is set,
        # and per-stream frame sequence tracking (a dropped frame must
        # fail loudly, not silently lose records).
        self._assembler = (
            ColumnAssembler(input_mapping) if input_mapping else None
        )
        self._seq = ReplayCursor(
            name=f"queue {qname_in!r}",
            on_drop=lambda _stream: _replay_counter().inc(queue=qname_in),
        )

    def next_batch(self, batch_size: int) -> list | dict[str, np.ndarray]:
        """Return up to ``batch_size`` records.

        Blocks until records arrive. Returns a *partial* batch when an
        :class:`EndPartition` marker is hit (partition boundary) and an
        empty/partial batch with ``should_stop() == True`` once
        :class:`EndOfFeed` is seen. Reference: ``TFNode.py:DataFeed.next_batch``.

        With an ``input_mapping``, the returned ``{tensor: array}``
        columns are SLICED from columnar wire chunks when the producer
        shipped them (zero-copy views while a batch lands inside one
        chunk); row-pickle chunks pay the legacy per-batch stacking.
        """
        if self.input_mapping is None:
            return self._next_raw(batch_size)
        if self._assembler is None:
            # degenerate empty mapping: no columns to slice — keep the
            # pre-columnar contract (stack rows, here into an empty dict)
            return columnize_rows(self._next_raw(batch_size), self.input_mapping)
        return self._next_columns(batch_size)

    def _check_seq(self, chunk: ColumnChunk) -> bool:
        """Frame-drop detection AND replay dedupe — the per-stream
        seq protocol (:class:`ReplayCursor`, shared with the pull
        plane's ``IngestFeed``) doubles as the elastic plane's replay
        cursor: duplicates (an elastic reconfigure re-feeding a stream
        a consumer partially saw, or a rejoiner seeded via
        :meth:`seed_cursor`) drop — counted in
        ``feed_replay_skipped_total`` — and forward gaps (a frame lost
        mid-stream, see the ``columnar.frame`` failpoint) raise instead
        of training on a hole."""
        return self._seq.check(chunk.stream, chunk.seq)

    def cursor(self) -> dict[str, int]:
        """The replay cursor: last consumed frame ``seq`` per live
        stream. An elastic consumer snapshots this alongside its train
        state; after a reconfigure re-feeds the stream, seeding a fresh
        feed with :meth:`seed_cursor` makes the already-consumed prefix
        drop silently (exactly-once, same data order)."""
        return self._seq.snapshot()

    def seed_cursor(self, cursor: dict[str, int]) -> None:
        """Adopt a replay cursor (see :meth:`cursor`): frames at or
        below each stream's seeded seq are treated as replayed
        duplicates and dropped instead of raising a gap."""
        self._seq.seed(cursor)

    def _ingest(self, item: Any, sp=None) -> Any:
        """Normalize a queue item: decode TCP-borne frames (zero-copy
        views over the received bytes) and run the sequence check on
        every columnar chunk. ``sp`` (the enclosing ``feed.queue_get``
        span) gets the frame's ``stream``/``seq`` as args — the
        consumer-side half of the per-frame span link the driver's
        ``feed.send`` carries, which ``tools/trace_merge.py`` stitches
        across processes."""
        if isinstance(item, ColumnarFrame):
            item = decode_frame(item.data, path="tcp")
        if isinstance(item, ColumnChunk):
            if sp is not None and item.stream is not None:
                sp.set(stream=item.stream, seq=item.seq)
            if failpoint("columnar.frame") == "drop":
                return _DROPPED
            if not self._check_seq(item):
                return _DROPPED  # replayed duplicate (elastic re-feed)
        elif isinstance(item, EndPartition):
            # Stream ids are per-partition (feed_partition mints one per
            # call), so the finished partition's seq entry is dead — a
            # long-running streaming job (one feed_partition per
            # micro-batch) would otherwise grow this dict forever. A
            # frame dropped at the very END of a stream is inherently
            # undetectable by seq-gap (there is no successor frame),
            # with or without this clear.
            self._seq.clear()
        return item

    def _next_raw(self, batch_size: int) -> list:
        """``next_batch`` core: up to ``batch_size`` raw records, no mapping."""
        batch: list[Any] = []
        while len(batch) < batch_size:
            take = batch_size - len(batch)
            if self._buffer:
                batch.extend(self._buffer[:take])
                del self._buffer[:take]
                continue
            if self.done_feeding:
                break
            # queue wait: time spent blocked on the push plane (the
            # feeder side of data-wait; feed.data_wait in prefetch.py
            # is the consumer side). Bounded by the feed-timeout policy
            # — a producer that stalled or died surfaces as a
            # descriptive FeedTimeout, not an eternal block.
            with obs_spans.span("feed.queue_get") as sp:
                item = self._pull()
                self._queue_in.task_done()
                item = self._ingest(item, sp)
            if item is _DROPPED:
                continue
            if isinstance(item, Marker) or item is None:
                if isinstance(item, EndPartition):
                    if batch:
                        break  # partial batch at partition boundary
                    continue  # nothing buffered; keep reading next partition
                # EndOfFeed / legacy None terminal marker
                self.done_feeding = True
                break
            elif isinstance(item, ColumnChunk):
                # mapping-less consumers want record lists: materialize
                self._buffer.extend(item.rows())
            elif isinstance(item, list):
                self._buffer.extend(item)
            else:  # single record (legacy per-item producers)
                batch.append(item)
            # drop the local before the next blocking pull: a ColumnChunk
            # held here would pin its ring slot and stall the producer
            item = None
        return batch

    def _next_columns(self, batch_size: int) -> dict[str, np.ndarray]:
        """``next_batch`` core for mapped feeds: accumulate pieces and
        assemble by slicing column views (zero-copy within one chunk)."""
        asm = self._assembler
        while len(asm) < batch_size:
            if self.done_feeding:
                break
            with obs_spans.span("feed.queue_get") as sp:
                item = self._pull()
                self._queue_in.task_done()
                item = self._ingest(item, sp)
            if item is _DROPPED:
                continue
            if isinstance(item, Marker) or item is None:
                if isinstance(item, EndPartition):
                    if len(asm):
                        break  # partial batch at partition boundary
                    continue
                self.done_feeding = True
                break
            elif isinstance(item, (ColumnChunk, list)):
                asm.push(item)
            else:  # single record (legacy per-item producers)
                asm.push([item])
            item = None  # see _next_raw: never hold a chunk across a pull
        return asm.take(batch_size)

    @property
    def feed_timeout(self) -> float | None:
        """The resolved pull-loop bound in seconds, or None (unbounded)
        while no policy exists. The constructor value wins; otherwise
        the driver-published manager KV (``TFCluster.train(
        feed_timeout=...)``) is probed each call until it appears —
        never latched as a default, so a publish that lands after the
        first pull still takes effect."""
        if self._feed_timeout is None:
            published = self.mgr.get(wire.FEED_TIMEOUT_KEY)
            if published is not None:
                self._feed_timeout = float(
                    wire.decode("kv.feed_timeout", published)["value"]
                )
        return self._feed_timeout

    def _pull(self):
        """One blocking pull off the input queue, bounded by the feed
        policy when one exists.

        Waits in short slices (so a policy published mid-wait is
        honored); once a policy is known, ``feed_timeout`` seconds of
        silence raise :class:`FeedTimeout` naming the queue and worker.
        With no policy (stream feeds, bare DataFeeds) the pull blocks
        indefinitely — quiet is not death there."""
        failpoint("datafeed.get")
        deadline = None
        while True:
            timeout = self.feed_timeout
            if deadline is None and timeout is not None:
                deadline = time.monotonic() + timeout
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise FeedTimeout(
                        f"no data on queue {self.qname_in!r} for worker "
                        f"{self.worker_index if self.worker_index is not None else '?'} "
                        f"within feed_timeout={timeout}s (producer "
                        "stalled or died)"
                    )
                wait = min(remaining, 5.0)
            else:
                wait = 5.0
            try:
                return self._queue_in.get(block=True, timeout=wait)
            except _queue.Empty:
                continue

    def batch_stream(self, batch_size: int, multiple_of: int = 1):
        """Yield fixed-size batches, buffering across partition boundaries.

        ``next_batch`` returns *partial* batches at every
        :class:`EndPartition` (the reference contract) — every training
        loop that wants steady shapes for ``jit`` must re-buffer them.
        This generator does that once, centrally: every yielded batch has
        exactly ``batch_size`` records — rounded down to a multiple of
        ``multiple_of`` so full batches shard — until the feed tail, which
        is trimmed to the largest multiple of ``multiple_of`` (pass
        ``jax.device_count()``; the sub-multiple remainder is dropped with
        a log line, like the reference's drop-remainder datasets).
        """
        from tensorflowonspark_tpu.utils.batching import fixed_size_batches

        mapping = self.input_mapping
        if mapping:
            # Columnar fast path: stream pieces (chunks / row lists) into
            # the slicing assembler; same fixed-size + tail-trim contract.
            yield from column_batches(
                self._pieces(batch_size), batch_size, multiple_of, mapping
            )
            return

        def records():
            while not self.should_stop():
                yield from self._next_raw(batch_size)

        yield from fixed_size_batches(
            records(),
            batch_size,
            multiple_of,
            assemble=lambda rows: list(rows),
        )

    def _pieces(self, batch_hint: int):
        """Pieces (ColumnChunk views / row lists) until feed end,
        ignoring partition boundaries (``batch_stream`` fills across
        them); leftovers buffered by ``next_batch`` drain first."""
        asm = self._assembler
        if len(asm):
            yield from asm.drain_pieces()  # next_batch leftovers first
        while not self.done_feeding:
            with obs_spans.span("feed.queue_get") as sp:
                item = self._pull()
                self._queue_in.task_done()
                item = self._ingest(item, sp)
            if item is _DROPPED or isinstance(item, EndPartition):
                continue
            if isinstance(item, Marker) or item is None:
                self.done_feeding = True
                return
            if isinstance(item, (ColumnChunk, list)):
                piece, item = item, None
                yield piece
                del piece  # see _next_raw: no chunk ref across a pull
            else:
                yield [item]

    def should_stop(self) -> bool:
        """True once the feed is exhausted. Reference: ``DataFeed.should_stop``."""
        return self.done_feeding

    def batch_results(self, results: Sequence[Any]) -> None:
        """Push one batch of inference results to the output queue.

        Contract (reference ``_inference`` equal-count rule): over a whole
        feed, exactly one result per input record, in order.
        """
        failpoint("datafeed.put_results")
        self._queue_out.put(list(results))

    def terminate(self) -> None:
        """Signal early termination and drain the input queue.

        Sets the node KV ``state`` to ``'terminating'`` so in-flight feeder
        tasks fast-drain their partitions instead of blocking on a full
        queue (reference: ``DataFeed.terminate`` + the ``state`` check at
        the top of ``TFSparkNode._train``).
        """
        logger.info("DataFeed terminating; draining input queue")
        self.mgr.set(
            wire.NODE_STATE_KEY,
            wire.encode("kv.node_state", value="terminating"),
        )
        # Idle window for "the queue is drained": policy-driven (bounded
        # by the feed timeout when one exists) rather than a hardcoded
        # constant, but still short — this is a quiet-period detector,
        # not a wait for more data.
        ft = self.feed_timeout
        idle = 3.0 if ft is None else min(3.0, ft)
        done = False
        while not done:
            try:
                item = self._queue_in.get(block=True, timeout=idle)
                self._queue_in.task_done()
                if isinstance(item, EndOfFeed) or item is None:
                    self.done_feeding = True
            except _queue.Empty:
                done = True

    def synchronized_batch_stream(
        self,
        batch_size: int,
        multiple_of: int = 1,
        stop_when=None,
    ):
        """Multi-controller-safe :meth:`batch_stream`.

        In multi-process (``jax.distributed``) training every process
        must run every collective: if one host's feed drains a wave
        earlier than another's, the short host leaves the training loop
        while the others enter the next jit step, and the program
        deadlocks in a psum (SURVEY.md §7 "hard parts": the all-hosts
        feed-exhausted agreement, the moral equivalent of
        ``queue.join()``). This generator closes that hole: before each
        yield, processes agree — via a tiny cross-process allgather —
        that *all* of them hold a full next batch. The first round where
        any process is short (exhausted, or ``stop_when()`` true —
        use that instead of ``break`` so early stop is also agreed),
        every process stops together; ragged tails are dropped, like the
        reference's drop-remainder datasets.

        Single-process: degrades to plain :meth:`batch_stream` (with
        ``stop_when`` honored) at zero collective cost.
        """
        import jax

        it = self.batch_stream(batch_size, multiple_of)
        if jax.process_count() == 1:
            for batch in it:
                if stop_when is not None and stop_when():
                    return
                yield batch
            return

        from jax.experimental import multihost_utils

        def n_records(b):
            if isinstance(b, dict):
                return len(next(iter(b.values())))
            return len(b)

        while True:
            batch = next(it, None)
            # Only a FULL batch counts: batch_stream's trimmed tail can be
            # shorter, and one process yielding a different local batch
            # size than the others breaks the very shape agreement this
            # method exists for.
            have = (
                batch is not None
                and n_records(batch) == batch_size
                and not (stop_when is not None and stop_when())
            )
            all_have = bool(
                multihost_utils.process_allgather(
                    np.asarray([1 if have else 0], np.int32)
                ).min()
            )
            if not all_have:
                if batch is not None:
                    logger.info(
                        "synchronized_batch_stream: dropping tail batch "
                        "(another process is exhausted)"
                    )
                return
            yield batch
