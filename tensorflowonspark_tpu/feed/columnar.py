"""Chunk-columnar wire format for the push feed plane.

SURVEY.md §3.2 names the per-item pickle-proxy tax as the reference's
feed bottleneck, and the PR-2-era plane still shipped every partition
chunk as a pickled *list of rows* that the node re-assembled with
``columnize_rows``/``np.stack`` per batch. tf.data (PAPERS.md,
arXiv:2101.12127) wins the same fight by moving input pipelines onto
contiguous columnar buffers; this module is that move for our wire:

- The DRIVER columnizes each partition chunk ONCE
  (:func:`columnize_records`): per-field contiguous ndarray buffers plus
  a small dtype/shape header, CRC-framed (:func:`encode_parts` /
  :func:`frame_bytes`).
- The NODE reconstructs columns as **zero-copy views** over the received
  buffer (:func:`decode_frame`): ``np.frombuffer`` slices, no per-row
  object churn. Over the shm ring the buffer IS the ring memory
  (refcounted frames — see ``native/shmring.py``); over TCP it is the
  one bytes object the manager proxy delivered; for node-local files it
  is an ``mmap`` (:func:`read_frames`).
- Batches are assembled by SLICING column views
  (:class:`ColumnAssembler` / :func:`column_batches`) instead of
  stacking rows: a batch that lands inside one chunk costs zero copies.

Anything non-columnizable (ragged shapes, object dtypes, mixed records,
bytes with trailing NULs — which numpy's ``S`` dtype would silently
trim) falls back to the versioned row-pickle path, chunk by chunk; the
two formats interleave freely on the same queue.

Frame layout (one logical wire record)::

    [0:4)    magic  b"TFC\\x01"           (3-byte tag + format version)
    [4:8)    u32 header_len
    [8:12)   u32 header_crc               (crc32 of the header bytes)
    [12:+hl) header                       (pickled dict, see below)
    ...      zero pad to 64-byte alignment
    ...      column payloads, each 64-aligned relative to payload start

Header dict: ``{"v": 1, "qname", "kind": dict|tuple|flat, "n",
"cols": [(key, dtype_str, shape, offset, nbytes)], "payload_crc",
"stream", "seq"}``. ``offset`` is relative to the (aligned) payload
start, so header size and payload layout are independent. ``stream`` /
``seq`` let the consumer detect a frame dropped mid-stream
(``DataFeed`` raises on a sequence gap — see the ``columnar.frame``
failpoint).

``payload_crc`` is the running crc32 over the column buffers (pads
excluded). The shm-ring producer skips it (``encode_parts(crc=False)``
→ ``payload_crc: None``): the transport is same-host memory whose
length framing + always-verified header CRC already catch truncation,
and the verify pass would force a full read of memory the consumer
otherwise only views. TCP- and file-borne frames carry and verify it;
``TFOS_COLUMNAR_CRC=0`` disables verification globally for trusted
transports.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import threading
from collections import deque
from typing import Any, Iterable, Iterator, Sequence
from zlib import crc32

import numpy as np

from tensorflowonspark_tpu.cluster import wire

logger = logging.getLogger(__name__)

__all__ = [
    "ALIGN",
    "MAGIC",
    "ColumnAssembler",
    "ColumnChunk",
    "ColumnarFrame",
    "column_batches",
    "columnize_records",
    "decode_frame",
    "encode_parts",
    "frame_bytes",
    "is_frame",
    "read_frames",
    "scan_frames",
    "write_frames",
]

MAGIC = b"TFC\x01"
_PREFIX = struct.Struct("<4sII")  # magic+version, header_len, header_crc
ALIGN = 64

# Payload CRC verification on decode (header CRC is always verified).
_VERIFY_PAYLOAD = os.environ.get("TFOS_COLUMNAR_CRC", "1") != "0"


def _align(n: int) -> int:
    return (n + ALIGN - 1) & ~(ALIGN - 1)


# -- obs ---------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: dict[str, Any] | None = None


def metrics() -> dict[str, Any]:
    """Feed-plane columnar counters in the process-global obs registry:
    frames/bytes/records per path (shm|tcp|manifest) plus the fallback
    counter (chunks that could not columnize, by reason)."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from tensorflowonspark_tpu.obs.registry import default_registry

                r = default_registry()
                _metrics = {
                    "frames": r.counter(
                        "feed_columnar_frames_total",
                        "columnar frames decoded, by transport path",
                    ),
                    "bytes": r.counter(
                        "feed_columnar_bytes_total",
                        "columnar payload bytes decoded, by transport path",
                    ),
                    "records": r.counter(
                        "feed_columnar_records_total",
                        "records carried by columnar frames, by path",
                    ),
                    "fallback": r.counter(
                        "feed_columnar_fallback_total",
                        "chunks that fell back to row-pickle, by reason",
                    ),
                }
    return _metrics


def _count_decode(chunk: "ColumnChunk", nbytes: int, path: str) -> None:
    m = metrics()
    m["frames"].inc(path=path)
    m["bytes"].inc(nbytes, path=path)
    m["records"].inc(chunk.n, path=path)


# -- chunk model -------------------------------------------------------------


class ColumnChunk:
    """One columnar chunk: per-field contiguous arrays over shared wire
    memory (or driver-built, pre-encode).

    ``kind`` records how the original rows were shaped so ``rows()`` can
    reconstruct them: ``"dict"`` (keys are field names), ``"tuple"``
    (keys are positions), ``"flat"`` (one anonymous column). Slicing
    (:meth:`view`) produces numpy views — the underlying frame buffer
    stays alive through the views' base chain, which is exactly the
    refcount that lets a ring slot outlive its pop.
    """

    __slots__ = ("kind", "keys", "arrays", "n", "qname", "stream", "seq")

    def __init__(
        self,
        kind: str,
        keys: Sequence[Any],
        arrays: Sequence[np.ndarray],
        qname: str | None = None,
        stream: str | None = None,
        seq: int = 0,
    ):
        self.kind = kind
        self.keys = tuple(keys)
        self.arrays = tuple(arrays)
        self.n = int(self.arrays[0].shape[0]) if self.arrays else 0
        self.qname = qname
        self.stream = stream
        self.seq = seq

    def __len__(self) -> int:
        return self.n

    def view(self, start: int, stop: int) -> "ColumnChunk":
        """Record-range slice as views (zero-copy)."""
        return ColumnChunk(
            self.kind,
            self.keys,
            tuple(a[start:stop] for a in self.arrays),
            qname=self.qname,
            stream=self.stream,
            seq=self.seq,
        )

    def materialize(self) -> "ColumnChunk":
        """Copy the columns out of their wire buffer, dropping the view
        base chain — releases the underlying ring slot / mmap NOW
        instead of when the views die (the drain's backpressure guard)."""
        return ColumnChunk(
            self.kind,
            self.keys,
            tuple(a.copy() for a in self.arrays),
            qname=self.qname,
            stream=self.stream,
            seq=self.seq,
        )

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.arrays)

    @property
    def is_view(self) -> bool:
        """Any column still backed by wire memory (ring slot / received
        bytes / mmap) — i.e. holding this chunk pins that buffer."""
        return any(a.base is not None for a in self.arrays)

    def columns(self) -> dict[Any, np.ndarray]:
        return dict(zip(self.keys, self.arrays))

    def by_mapping(self, input_mapping: dict[str, str]) -> dict[str, np.ndarray]:
        """{tensor_name: column} per the feed's ``input_mapping`` —
        the sliced-column replacement for ``columnize_rows``. Field
        resolution mirrors it: dict records by field name (loud on a
        missing field), tuple records by position with an arity check."""
        if self.kind == "dict":
            cols = self.columns()
            out: dict[str, np.ndarray] = {}
            for field, tensor in input_mapping.items():
                if field not in cols:
                    raise KeyError(
                        f"input_mapping field {field!r} not present in a "
                        f"dict record (record keys: "
                        f"{sorted(map(str, self.keys))}); "
                        f"mapping={input_mapping}"
                    )
                out[tensor] = cols[field]
            return out
        if self.kind == "tuple":
            names = list(input_mapping)
            if len(self.keys) != len(names):
                raise ValueError(
                    f"input_mapping has {len(names)} columns {names} but "
                    f"records have {len(self.keys)} fields; for tuple "
                    "records the mapping must name every field, in order"
                )
            return dict(zip(input_mapping.values(), self.arrays))
        # flat records: only an unambiguous single-tensor mapping works
        if len(input_mapping) == 1:
            (tensor,) = input_mapping.values()
            return {tensor: self.arrays[0]}
        raise ValueError(
            "flat (scalar/array) records cannot satisfy a multi-field "
            f"input_mapping {input_mapping}"
        )

    def rows(self) -> list[Any]:
        """Materialize back to the original record shapes (row views for
        array fields, numpy scalars for scalar fields) — the path for
        mapping-less consumers that want plain record lists."""
        if self.kind == "flat":
            return list(self.arrays[0])
        if self.kind == "tuple":
            return list(zip(*self.arrays))
        return [
            {k: a[i] for k, a in zip(self.keys, self.arrays)}
            for i in range(self.n)
        ]


class ColumnarFrame:
    """An encoded frame riding a pickle transport (the TCP manager
    proxy): pickles as one bytes payload — no per-row object churn —
    and is decoded into zero-copy views on the consumer side."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data

    def __reduce__(self):
        return (ColumnarFrame, (self.data,))


# -- columnization -----------------------------------------------------------


def _scalar_kinds(v: Any) -> bool:
    return isinstance(v, (bool, int, float, complex, np.generic))


def _scalar_class(v: Any) -> str | None:
    """Dtype-kind bucket for the lossless-only scalar gate: mixing
    buckets (bool+int, int+float, ...) would let ``np.asarray`` coerce
    — silently lossy — so mixed chunks must fall back to row-pickle.
    Order matters: ``bool`` subclasses ``int``, ``np.float64``
    subclasses ``float``."""
    if isinstance(v, np.generic):
        return v.dtype.kind  # b,i,u,f,c
    if isinstance(v, bool):
        return "b"
    if isinstance(v, int):
        return "i"
    if isinstance(v, float):
        return "f"
    if isinstance(v, complex):
        return "c"
    return None


def _column(values: list[Any]) -> np.ndarray | None:
    """One contiguous column from per-row field values, or None when the
    field is not columnizable (ragged/object/mixed)."""
    v0 = values[0]
    if isinstance(v0, np.ndarray):
        if v0.dtype.hasobject or v0.dtype.names:
            return None
        shape, dtype = v0.shape, v0.dtype
        for v in values[1:]:
            if (
                not isinstance(v, np.ndarray)
                or v.shape != shape
                or v.dtype != dtype
            ):
                return None
        out = np.empty((len(values),) + shape, dtype)
        for i, v in enumerate(values):
            out[i] = v
        return out
    if isinstance(v0, (bytes, bytearray)):
        ln = len(v0)
        for v in values:
            if not isinstance(v, (bytes, bytearray)) or len(v) != ln:
                return None
            # numpy S-dtype trims trailing NULs on read — silently lossy
            if v[-1:] == b"\x00":
                return None
        return np.array(values, dtype=f"S{max(ln, 1)}")
    if isinstance(v0, str):
        ln = len(v0)
        for v in values:
            if not isinstance(v, str) or len(v) != ln or v[-1:] == "\x00":
                return None
        return np.array(values, dtype=f"U{max(ln, 1)}")
    if _scalar_kinds(v0):
        cls = _scalar_class(v0)
        if any(_scalar_class(v) != cls for v in values[1:]):
            return None  # mixed kinds: asarray would coerce (lossy)
        try:
            arr = np.asarray(values)
        except (ValueError, OverflowError):
            return None
        if arr.dtype.hasobject or arr.shape != (len(values),):
            return None
        return arr
    return None


def columnize_records(records: Sequence[Any]) -> ColumnChunk | None:
    """Columnize one chunk of rows ONCE, driver-side. Returns None when
    the chunk must ride the row-pickle fallback (the caller counts the
    fallback and ships the original list)."""
    if not records:
        return None
    first = records[0]
    if isinstance(first, dict):
        keys = tuple(first.keys())
        keyset = set(keys)
        for r in records[1:]:
            if not isinstance(r, dict) or set(r.keys()) != keyset:
                return None
        arrays = []
        for k in keys:
            col = _column([r[k] for r in records])
            if col is None:
                return None
            arrays.append(col)
        return ColumnChunk("dict", keys, arrays)
    if isinstance(first, (tuple, list)):
        arity = len(first)
        for r in records[1:]:
            if not isinstance(r, (tuple, list)) or len(r) != arity:
                return None
        arrays = []
        for i in range(arity):
            col = _column([r[i] for r in records])
            if col is None:
                return None
            arrays.append(col)
        return ColumnChunk("tuple", tuple(range(arity)), arrays)
    col = _column(list(records))
    if col is None:
        return None
    return ColumnChunk("flat", (None,), (col,))


# -- encode ------------------------------------------------------------------

_PAD = b"\x00" * ALIGN


def encode_parts(
    chunk: ColumnChunk,
    qname: str | None = None,
    stream: str | None = None,
    seq: int = 0,
    crc: bool = True,
) -> list[Any]:
    """Encode to a scatter list ``[bytes | ndarray, ...]`` whose
    concatenation is the frame — the shm ring pushes these straight from
    numpy memory (``ShmRing.push_parts``) with no assembly copy.

    ``crc=False`` skips the payload checksum (``payload_crc: None``) —
    the same-host ring path, where the extra full pass over the buffers
    costs more than the memory transport can ever corrupt."""
    arrays = [np.ascontiguousarray(a) for a in chunk.arrays]
    cols = []
    off = 0
    payload_crc: int | None = 0 if crc else None
    for k, a in zip(chunk.keys, arrays):
        nb = a.nbytes
        cols.append((k, a.dtype.str, a.shape, off, nb))
        if crc:
            payload_crc = crc32(a, payload_crc)
        off = _align(off + nb)
    header = pickle.dumps(
        # Declared-order encode keeps the pickled header byte-identical
        # to every frame ever written (schema: columnar.frame_header).
        wire.encode(
            "columnar.frame_header",
            v=1,
            qname=qname,
            kind=chunk.kind,
            n=int(chunk.n),
            cols=cols,
            payload_crc=payload_crc,
            stream=stream,
            seq=int(seq),
        ),
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    head = _PREFIX.pack(MAGIC, len(header), crc32(header)) + header
    parts: list[Any] = [head + _PAD[: _align(len(head)) - len(head)]]
    for (_, _, _, coff, nb), a in zip(cols, arrays):
        parts.append(a)
        pad = _align(nb) - nb
        if pad:
            parts.append(_PAD[:pad])
    return parts


def frame_bytes(
    chunk: ColumnChunk,
    qname: str | None = None,
    stream: str | None = None,
    seq: int = 0,
    crc: bool = True,
) -> bytes:
    """The frame as one bytes object (TCP / file transports)."""
    return b"".join(
        p.tobytes() if isinstance(p, np.ndarray) else p
        for p in encode_parts(
            chunk, qname=qname, stream=stream, seq=seq, crc=crc
        )
    )


def parts_nbytes(parts: list[Any]) -> int:
    return sum(
        p.nbytes if isinstance(p, np.ndarray) else len(p) for p in parts
    )


# -- decode ------------------------------------------------------------------


def is_frame(buf) -> bool:
    """True when ``buf`` starts with the columnar frame magic."""
    try:
        mv = memoryview(buf)
    except TypeError:
        return False
    return len(mv) >= _PREFIX.size and bytes(mv[:4]) == MAGIC


def decode_frame(buf, path: str | None = None) -> ColumnChunk:
    """Decode a frame into column views over ``buf`` (zero-copy: the
    views' base chain keeps ``buf`` — ring slot, bytes object, or mmap —
    alive until the batch is consumed or transferred). Raises
    ValueError on magic/version/CRC mismatch."""
    mv = memoryview(buf)
    if bytes(mv[:3]) != MAGIC[:3]:
        raise ValueError("not a columnar frame (bad magic)")
    if mv[3] != MAGIC[3]:
        raise ValueError(
            f"unsupported columnar frame version {mv[3]} (have {MAGIC[3]})"
        )
    _, hlen, hcrc = _PREFIX.unpack_from(mv, 0)
    header_bytes = bytes(mv[_PREFIX.size : _PREFIX.size + hlen])
    if len(header_bytes) != hlen or crc32(header_bytes) != hcrc:
        raise ValueError("columnar frame header CRC mismatch (corrupt frame)")
    h = wire.decode("columnar.frame_header", pickle.loads(header_bytes))
    payload_start = _align(_PREFIX.size + hlen)
    verify = _VERIFY_PAYLOAD and h.get("payload_crc") is not None
    keys, arrays = [], []
    crc = 0
    for k, dt, shape, off, nb in h["cols"]:
        dtype = np.dtype(dt)
        a = np.frombuffer(
            mv, dtype=dtype, count=nb // dtype.itemsize if dtype.itemsize else 0,
            offset=payload_start + off,
        ).reshape(shape)
        if verify:
            crc = crc32(a, crc)
        keys.append(k)
        arrays.append(a)
    if verify and crc != h["payload_crc"]:
        raise ValueError("columnar frame payload CRC mismatch (corrupt frame)")
    chunk = ColumnChunk(
        h["kind"],
        keys,
        arrays,
        qname=h.get("qname"),
        stream=h.get("stream"),
        seq=int(h.get("seq", 0)),
    )
    if path is not None:
        _count_decode(chunk, len(mv), path)
    return chunk


def _frame_header(mv, offset: int = 0) -> tuple[dict, int]:
    """(header dict, frame span) at ``offset`` — header bytes only, no
    payload read. Shared by the file reader's framing step and the
    header-only scans below."""
    _, hlen, _ = _PREFIX.unpack_from(mv, offset)
    header_bytes = bytes(
        mv[offset + _PREFIX.size : offset + _PREFIX.size + hlen]
    )
    h = wire.decode("columnar.frame_header", pickle.loads(header_bytes))
    payload = 0
    for _, _, _, off, nb in h["cols"]:
        payload = max(payload, _align(off + nb))
    return h, _align(_PREFIX.size + hlen) + payload


def frame_span(buf, offset: int = 0) -> int:
    """Total byte length of the frame starting at ``offset`` in ``buf``
    (header + aligned payload) — the file reader's framing step."""
    return _frame_header(memoryview(buf), offset)[1]


def scan_frames(path: str) -> Iterator[tuple[int, int, int]]:
    """``(byte_offset, span, record_count)`` of each frame in a framed
    file, via header-only reads — payload bytes are never touched. This
    is the cheap size probe behind manifest planning
    (``feed.manifest.manifest_records`` / ``split_manifest``) and the
    random-access frame index (``data.grain_source``): splitting a
    multi-GB shard file across nodes costs one metadata pass, not a
    full read."""
    import mmap as _mmap

    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size == 0:
            return
        mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
    mv = memoryview(mm)
    off = 0
    while off + _PREFIX.size <= size:
        h, span = _frame_header(mv, off)
        yield off, span, int(h.get("n", 0))
        off += _align(span)


# -- framed files (manifest path) --------------------------------------------


def write_frames(
    path: str,
    records: Iterable[Any],
    records_per_frame: int = 1024,
    stream: str | None = None,
) -> int:
    """Write records to ``path`` as a sequence of 64-aligned columnar
    frames (the node-local file format ``FileManifest(format=
    "columnar")`` reads back zero-copy via mmap). Records must be
    columnizable — ragged/object data should stay on tfrecord/lines.
    Returns the record count."""
    n = 0
    seq = 0
    with open(path, "wb") as f:
        batch: list[Any] = []

        def flush():
            nonlocal seq
            if not batch:
                return
            chunk = columnize_records(batch)
            if chunk is None:
                raise ValueError(
                    "records are not columnizable (ragged/object data); "
                    "use tfrecord or lines manifests instead"
                )
            data = frame_bytes(chunk, stream=stream, seq=seq)
            f.write(data)
            f.write(_PAD[: _align(len(data)) - len(data)])
            seq += 1

        for r in records:
            batch.append(r)
            n += 1
            if len(batch) >= records_per_frame:
                flush()
                batch = []
        flush()
    return n


def read_frames(path: str, *, frame_cache=None) -> Iterator[ColumnChunk]:
    """Yield the ColumnChunks of a framed file as zero-copy views over
    one shared mmap (kept alive by the views' base chain).

    ``frame_cache`` (a ``cachetier.FrameCache``) optionally fronts the
    payload reads: the local mmap still serves the header walk (a few
    pages), but each frame's payload is fetched through the shared
    read-through tier — so N co-located readers of one file fault its
    payload bytes in from backing storage ONCE, fleet-wide. A cache
    miss/outage (``get`` → None) decodes from the local mmap exactly as
    before; frames are immutable, so the two paths are byte-identical.
    """
    import mmap as _mmap

    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size == 0:
            return
        mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
    mv = memoryview(mm)
    off = 0
    while off + _PREFIX.size <= size:
        span = frame_span(mv, off)
        blob = (
            frame_cache.get(path, off, span)
            if frame_cache is not None
            else None
        )
        if blob is not None:
            yield decode_frame(memoryview(blob), path="manifest")
        else:
            yield decode_frame(mv[off : off + span], path="manifest")
        off += _align(span)


# -- batch assembly ----------------------------------------------------------


class ColumnAssembler:
    """Accumulates pieces — row lists or :class:`ColumnChunk` — and
    assembles column batches by SLICING. A batch that lands inside one
    chunk is pure views (zero-copy); one that crosses pieces pays a
    single per-column concatenate; a row-list piece pays the legacy
    ``columnize_rows`` for exactly its records."""

    #: Cap on wire-view bytes the assembler may pin across a blocking
    #: pull. A batch assembled from ring-backed views freezes the shm
    #: tail at its oldest frame until the batch completes; a single
    #: batch bigger than the ring would therefore starve the producer of
    #: push space forever (the drain's per-frame guard cannot see
    #: consumer-side accumulation). Past this cap every held view piece
    #: is copied out — the slots release, the tail advances, the feed
    #: keeps flowing; only outsized batches pay the copy.
    MATERIALIZE_HELD_BYTES = 16 << 20

    def __init__(self, input_mapping: dict[str, str]):
        self.mapping = input_mapping
        self._pieces: deque = deque()
        self._count = 0

    def __len__(self) -> int:
        return self._count

    def push(self, piece) -> None:
        n = len(piece)
        if not n:
            return
        self._pieces.append(piece)
        self._count += n
        held = sum(
            p.nbytes
            for p in self._pieces
            if isinstance(p, ColumnChunk) and p.is_view
        )
        if held > self.MATERIALIZE_HELD_BYTES:
            self._pieces = deque(
                p.materialize()
                if isinstance(p, ColumnChunk) and p.is_view
                else p
                for p in self._pieces
            )

    def drain_pieces(self) -> Iterator[Any]:
        """Hand the buffered pieces back unassembled (``batch_stream``
        taking over a feed that ``next_batch`` partially consumed)."""
        while self._pieces:
            piece = self._pieces.popleft()
            self._count -= len(piece)
            yield piece

    def take(self, k: int) -> dict[str, np.ndarray]:
        """Assemble exactly ``min(k, len(self))`` records."""
        from tensorflowonspark_tpu.feed.datafeed import columnize_rows

        k = min(k, self._count)
        mapped: list[dict[str, np.ndarray]] = []
        need = k
        while need:
            head = self._pieces[0]
            n = len(head)
            take = min(need, n)
            if isinstance(head, ColumnChunk):
                part = head if take == n else head.view(0, take)
                mapped.append(part.by_mapping(self.mapping))
            else:
                mapped.append(columnize_rows(list(head[:take]), self.mapping))
            if take == n:
                self._pieces.popleft()
            elif isinstance(head, ColumnChunk):
                self._pieces[0] = head.view(take, n)
            else:
                self._pieces[0] = head[take:]
            need -= take
        self._count -= k
        if not mapped:
            return columnize_rows([], self.mapping)
        if len(mapped) == 1:
            return mapped[0]
        return {
            key: np.concatenate([m[key] for m in mapped])
            for key in mapped[0]
        }


def column_batches(
    pieces: Iterable[Any],
    batch_size: int,
    multiple_of: int,
    input_mapping: dict[str, str],
) -> Iterator[dict[str, np.ndarray]]:
    """Fixed-size column batches from a stream of pieces (row lists /
    chunks) — ``utils.batching.fixed_size_batches`` semantics (steady
    shapes, tail trimmed to ``multiple_of``, sub-multiple remainder
    dropped loudly) via slicing instead of per-record stacking."""
    batch_size -= batch_size % multiple_of
    if batch_size == 0:
        raise ValueError(
            f"batch_size < multiple_of ({multiple_of}); nothing to yield"
        )
    asm = ColumnAssembler(input_mapping)
    for piece in pieces:
        asm.push(piece)
        while len(asm) >= batch_size:
            yield asm.take(batch_size)
    tail = len(asm) - len(asm) % multiple_of
    if len(asm) % multiple_of:
        logger.warning(
            "dropping %d tail records (not a multiple of %d)",
            len(asm) % multiple_of,
            multiple_of,
        )
    if tail:
        yield asm.take(tail)
