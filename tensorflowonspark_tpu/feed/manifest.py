"""Manifest feeding — node-side feeders over the push control plane.

The measured push-plane ceiling (BASELINE.md "Push-plane ceiling",
`benchmarks/feed_plane.py`) is ~0.5–0.7 GB/s aggregate from one driver
host: every byte of ``InputMode.SPARK`` crosses the driver. The
reference never had this problem because its feed tasks ran *on the
executors* with HDFS data locality — the driver shipped closures, not
bytes (SURVEY.md §3.2).

This module restores that property inside SPARK mode: the driver feeds
:class:`FileManifest` records (tiny — a path and a format), and the
node-side :class:`ManifestFeed` expands each manifest into its records
by reading the file locally. Driver traffic drops from O(dataset bytes)
to O(number of files); assignment, ordering, epochs, and shutdown keep
the exact ``cluster.train`` semantics (manifests are ordinary records
on the existing queue plane).

Usage::

    # driver: ship paths, not bytes
    cluster.train([[FileManifest(p) for p in shard] for shard in shards])

    # node (map_fun): expand locally
    feed = ManifestFeed(ctx.get_data_feed())
    while not feed.should_stop():
        rows = feed.next_batch(batch_size)

When the files live on shared storage (NFS/GCS/HDFS-FUSE) every node
can read any manifest; with node-local storage, partition the manifests
to match file placement — the driver controls assignment either way.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Sequence

__all__ = [
    "FileManifest",
    "ManifestFeed",
    "manifest_records",
    "plan_manifests",
    "read_manifest",
    "read_manifest_chunks",
    "split_manifest",
]


@dataclasses.dataclass(frozen=True)
class FileManifest:
    """One node-readable unit of input: a file (or a record range of one).

    ``format``: ``'tfrecord'`` (rows decoded via the native codec +
    ``dfutil.fromTFExample``), ``'lines'`` (text lines, stripped), or
    ``'columnar'`` (a file of 64-aligned columnar frames written by
    ``feed.columnar.write_frames`` — read back as zero-copy column
    views over one shared mmap; ``ManifestFeed.batch_stream`` slices
    batches straight out of the chunks). Custom formats: pass a
    ``reader`` callable to :class:`ManifestFeed` instead.
    ``start``/``stop`` bound the record index range (Python slice
    semantics), so one large file can be split across nodes.
    """

    path: str
    format: str = "tfrecord"
    start: int = 0
    stop: int | None = None
    binary_features: tuple[str, ...] = ()


def read_manifest(
    m: FileManifest, reader: Callable[[FileManifest], Iterator[Any]] | None = None
) -> Iterator[Any]:
    """Yield the records a manifest names, reading the file locally."""
    if reader is not None:
        yield from _sliced(reader(m), m)
        return
    if m.format == "tfrecord":
        from tensorflowonspark_tpu.data import dfutil
        from tensorflowonspark_tpu.native.tfrecord import read_records

        # slice the SERIALIZED stream, decode only kept records: a node
        # taking the tail of a shared file must not pay proto decoding
        # for every record it skips
        for s in _sliced(read_records(m.path), m):
            yield dfutil.fromTFExample(s, list(m.binary_features))
    elif m.format == "lines":
        with open(m.path) as f:
            yield from _sliced((line.rstrip("\n") for line in f), m)
    elif m.format == "columnar":
        for chunk in read_manifest_chunks(m):
            yield from chunk.rows()
    else:
        raise ValueError(
            f"unknown manifest format {m.format!r}; use 'tfrecord', "
            "'lines', 'columnar', or pass reader= to ManifestFeed"
        )


def read_manifest_chunks(m: FileManifest):
    """ColumnChunks of a ``'columnar'`` manifest, honoring its
    ``start``/``stop`` record range by chunk-slicing (views — the mmap
    stays shared)."""
    from tensorflowonspark_tpu.feed.columnar import read_frames

    pos = 0
    for chunk in read_frames(m.path):
        lo = max(m.start - pos, 0)
        hi = len(chunk) if m.stop is None else min(m.stop - pos, len(chunk))
        pos += len(chunk)
        if hi <= lo:
            if m.stop is not None and pos >= m.stop:
                return
            continue
        yield chunk if (lo, hi) == (0, len(chunk)) else chunk.view(lo, hi)


def plan_manifests(
    manifests: Sequence[FileManifest], num_shards: int
) -> list[list[FileManifest]]:
    """Deterministic round-robin shard assignment — the driver side of
    the pull plane's manifest planning (``TFCluster.assign_shards``).

    Round-robin (like ``TFCluster.train``'s partition assignment) keeps
    per-shard record statistics close to the input distribution when
    file sizes vary. Determinism is a replay requirement, not a
    nicety: an elastic reconfigure re-plans over the surviving roster,
    and a restarted driver must hand every node the same shard it held
    before, or the seeded replay cursors point at the wrong streams.
    Shards may be empty when ``len(manifests) < num_shards`` — a node
    with an empty shard sees an immediately-exhausted feed, not an
    error (skewed file counts are normal at small scale).
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    ms = list(manifests)
    return [ms[i::num_shards] for i in range(num_shards)]


def manifest_records(
    m: FileManifest,
    reader: Callable[[FileManifest], Iterator[Any]] | None = None,
) -> int:
    """Record count a manifest names. For ``'columnar'`` manifests this
    is a header-only frame scan (payload bytes untouched — splitting a
    multi-GB file costs one metadata pass); other formats pay a full
    read."""
    if reader is None and m.format == "columnar":
        from tensorflowonspark_tpu.feed.columnar import scan_frames

        total = sum(n for _, _, n in scan_frames(m.path))
        stop = total if m.stop is None else min(m.stop, total)
        return max(0, stop - min(m.start, stop))
    return sum(1 for _ in read_manifest(m, reader))


def split_manifest(
    m: FileManifest,
    n: int,
    reader: Callable[[FileManifest], Iterator[Any]] | None = None,
) -> list[FileManifest]:
    """Split one manifest into at most ``n`` contiguous record-range
    manifests (sizes differ by at most one; empties dropped) so a
    single large file can feed many nodes. Contiguous ranges keep each
    shard a sequential read of its region."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    total = manifest_records(m, reader)
    k, rem = divmod(total, n)
    out: list[FileManifest] = []
    lo = 0
    for i in range(n):
        hi = lo + k + (1 if i < rem else 0)
        if hi > lo:
            out.append(
                dataclasses.replace(m, start=m.start + lo, stop=m.start + hi)
            )
        lo = hi
    return out


def _sliced(rows: Iterator[Any], m: FileManifest) -> Iterator[Any]:
    import itertools

    if m.start or m.stop is not None:
        return itertools.islice(rows, m.start, m.stop)
    return rows


class ManifestFeed:
    """Expand driver-fed :class:`FileManifest` records into data records.

    Wraps a :class:`~tensorflowonspark_tpu.feed.datafeed.DataFeed`: each
    record pulled from the underlying feed must be a FileManifest (or
    whatever ``reader`` understands); its records stream out of
    :meth:`next_batch` without ever crossing the driver.
    ``should_stop`` matches DataFeed (false until the feed ends AND the
    last manifest is drained), so existing training loops work
    unchanged. One deliberate contract difference: batches fill across
    file AND partition/epoch boundaries (manifests are pulled one at a
    time, so DataFeed's partial-batch-at-EndPartition signal never
    fires here) — steady batch shapes are what jitted training wants.
    Callers needing strict epoch separation should make one ``train``
    + drain cycle per epoch instead of ``num_epochs > 1``.
    """

    def __init__(
        self,
        feed,
        reader: Callable[[FileManifest], Iterator[Any]] | None = None,
    ):
        self.feed = feed
        self.reader = reader
        self._iter: Iterator[Any] | None = None

    def should_stop(self) -> bool:
        return self._iter is None and self.feed.should_stop()

    def next_batch(self, batch_size: int) -> list[Any]:
        """Up to ``batch_size`` records; empty once the feed has ended
        and the last manifest is drained."""
        out: list[Any] = []
        while len(out) < batch_size:
            if self._iter is not None:
                try:
                    out.append(next(self._iter))
                    continue
                except StopIteration:
                    self._iter = None
            got = self.feed.next_batch(1)
            if not got:
                break  # EndOfFeed (DataFeed returns [] only then)
            self._iter = read_manifest(got[0], self.reader)
        return out

    def batch_stream(
        self,
        batch_size: int,
        multiple_of: int = 1,
        input_mapping: dict[str, str] | None = None,
    ):
        """Fixed-size batches, exactly like ``DataFeed.batch_stream``
        (steady jit shapes; the feed tail trims to ``multiple_of``).
        Manifest records are rows, so an ``input_mapping`` for column
        assembly is taken here rather than from the underlying feed
        (whose records are manifests, not rows)."""
        from tensorflowonspark_tpu.utils.batching import fixed_size_batches

        if input_mapping is not None:
            from tensorflowonspark_tpu.feed.columnar import column_batches

            # Columnar manifests contribute whole chunks (batches are
            # then SLICED column views); other formats contribute row
            # lists that pay columnize_rows per batch, as before.
            yield from column_batches(
                self._pieces(batch_size),
                batch_size,
                multiple_of,
                input_mapping,
            )
            return

        def records():
            while not self.should_stop():
                yield from self.next_batch(batch_size)

        yield from fixed_size_batches(
            records(), batch_size, multiple_of, assemble=lambda rows: list(rows)
        )

    def _pieces(self, batch_hint: int):
        """Pieces (ColumnChunk / row lists) across the fed manifests —
        starting with the remainder of a manifest a prior ``next_batch``
        call partially consumed (``self._iter``)."""
        import itertools

        def row_pieces(it):
            while True:
                rows = list(itertools.islice(it, max(batch_hint, 1)))
                if not rows:
                    return
                yield rows

        if self._iter is not None:
            leftover, self._iter = self._iter, None
            yield from row_pieces(leftover)
        while True:
            got = self.feed.next_batch(1)
            if not got:
                return
            m = got[0]
            if (
                self.reader is None
                and isinstance(m, FileManifest)
                and m.format == "columnar"
            ):
                yield from read_manifest_chunks(m)
                continue
            yield from row_pieces(read_manifest(m, self.reader))

    def terminate(self) -> None:
        self.feed.terminate()
