"""Manifest feeding — node-side feeders over the push control plane.

The measured push-plane ceiling (BASELINE.md "Push-plane ceiling",
`benchmarks/feed_plane.py`) is ~0.5–0.7 GB/s aggregate from one driver
host: every byte of ``InputMode.SPARK`` crosses the driver. The
reference never had this problem because its feed tasks ran *on the
executors* with HDFS data locality — the driver shipped closures, not
bytes (SURVEY.md §3.2).

This module restores that property inside SPARK mode: the driver feeds
:class:`FileManifest` records (tiny — a path and a format), and the
node-side :class:`ManifestFeed` expands each manifest into its records
by reading the file locally. Driver traffic drops from O(dataset bytes)
to O(number of files); assignment, ordering, epochs, and shutdown keep
the exact ``cluster.train`` semantics (manifests are ordinary records
on the existing queue plane).

Usage::

    # driver: ship paths, not bytes
    cluster.train([[FileManifest(p) for p in shard] for shard in shards])

    # node (map_fun): expand locally
    feed = ManifestFeed(ctx.get_data_feed())
    while not feed.should_stop():
        rows = feed.next_batch(batch_size)

When the files live on shared storage (NFS/GCS/HDFS-FUSE) every node
can read any manifest; with node-local storage, partition the manifests
to match file placement — the driver controls assignment either way.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

__all__ = ["FileManifest", "ManifestFeed", "read_manifest"]


@dataclasses.dataclass(frozen=True)
class FileManifest:
    """One node-readable unit of input: a file (or a record range of one).

    ``format``: ``'tfrecord'`` (rows decoded via the native codec +
    ``dfutil.fromTFExample``) or ``'lines'`` (text lines, stripped).
    Custom formats: pass a ``reader`` callable to :class:`ManifestFeed`
    instead. ``start``/``stop`` bound the record index range (Python
    slice semantics), so one large file can be split across nodes.
    """

    path: str
    format: str = "tfrecord"
    start: int = 0
    stop: int | None = None
    binary_features: tuple[str, ...] = ()


def read_manifest(
    m: FileManifest, reader: Callable[[FileManifest], Iterator[Any]] | None = None
) -> Iterator[Any]:
    """Yield the records a manifest names, reading the file locally."""
    if reader is not None:
        yield from _sliced(reader(m), m)
        return
    if m.format == "tfrecord":
        from tensorflowonspark_tpu.data import dfutil
        from tensorflowonspark_tpu.native.tfrecord import read_records

        # slice the SERIALIZED stream, decode only kept records: a node
        # taking the tail of a shared file must not pay proto decoding
        # for every record it skips
        for s in _sliced(read_records(m.path), m):
            yield dfutil.fromTFExample(s, list(m.binary_features))
    elif m.format == "lines":
        with open(m.path) as f:
            yield from _sliced((line.rstrip("\n") for line in f), m)
    else:
        raise ValueError(
            f"unknown manifest format {m.format!r}; use 'tfrecord', "
            "'lines', or pass reader= to ManifestFeed"
        )


def _sliced(rows: Iterator[Any], m: FileManifest) -> Iterator[Any]:
    import itertools

    if m.start or m.stop is not None:
        return itertools.islice(rows, m.start, m.stop)
    return rows


class ManifestFeed:
    """Expand driver-fed :class:`FileManifest` records into data records.

    Wraps a :class:`~tensorflowonspark_tpu.feed.datafeed.DataFeed`: each
    record pulled from the underlying feed must be a FileManifest (or
    whatever ``reader`` understands); its records stream out of
    :meth:`next_batch` without ever crossing the driver.
    ``should_stop`` matches DataFeed (false until the feed ends AND the
    last manifest is drained), so existing training loops work
    unchanged. One deliberate contract difference: batches fill across
    file AND partition/epoch boundaries (manifests are pulled one at a
    time, so DataFeed's partial-batch-at-EndPartition signal never
    fires here) — steady batch shapes are what jitted training wants.
    Callers needing strict epoch separation should make one ``train``
    + drain cycle per epoch instead of ``num_epochs > 1``.
    """

    def __init__(
        self,
        feed,
        reader: Callable[[FileManifest], Iterator[Any]] | None = None,
    ):
        self.feed = feed
        self.reader = reader
        self._iter: Iterator[Any] | None = None

    def should_stop(self) -> bool:
        return self._iter is None and self.feed.should_stop()

    def next_batch(self, batch_size: int) -> list[Any]:
        """Up to ``batch_size`` records; empty once the feed has ended
        and the last manifest is drained."""
        out: list[Any] = []
        while len(out) < batch_size:
            if self._iter is not None:
                try:
                    out.append(next(self._iter))
                    continue
                except StopIteration:
                    self._iter = None
            got = self.feed.next_batch(1)
            if not got:
                break  # EndOfFeed (DataFeed returns [] only then)
            self._iter = read_manifest(got[0], self.reader)
        return out

    def batch_stream(
        self,
        batch_size: int,
        multiple_of: int = 1,
        input_mapping: dict[str, str] | None = None,
    ):
        """Fixed-size batches, exactly like ``DataFeed.batch_stream``
        (steady jit shapes; the feed tail trims to ``multiple_of``).
        Manifest records are rows, so an ``input_mapping`` for column
        assembly is taken here rather than from the underlying feed
        (whose records are manifests, not rows)."""
        from tensorflowonspark_tpu.feed.datafeed import columnize_rows
        from tensorflowonspark_tpu.utils.batching import fixed_size_batches

        def records():
            while not self.should_stop():
                yield from self.next_batch(batch_size)

        assemble = (
            (lambda rows: columnize_rows(list(rows), input_mapping))
            if input_mapping is not None
            else (lambda rows: list(rows))
        )
        yield from fixed_size_batches(
            records(), batch_size, multiple_of, assemble=assemble
        )

    def terminate(self) -> None:
        self.feed.terminate()
