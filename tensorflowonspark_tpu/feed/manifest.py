"""Manifest feeding — node-side feeders over the push control plane.

The measured push-plane ceiling (BASELINE.md "Push-plane ceiling",
`benchmarks/feed_plane.py`) is ~0.5–0.7 GB/s aggregate from one driver
host: every byte of ``InputMode.SPARK`` crosses the driver. The
reference never had this problem because its feed tasks ran *on the
executors* with HDFS data locality — the driver shipped closures, not
bytes (SURVEY.md §3.2).

This module restores that property inside SPARK mode: the driver feeds
:class:`FileManifest` records (tiny — a path and a format), and the
node-side :class:`ManifestFeed` expands each manifest into its records
by reading the file locally. Driver traffic drops from O(dataset bytes)
to O(number of files); assignment, ordering, epochs, and shutdown keep
the exact ``cluster.train`` semantics (manifests are ordinary records
on the existing queue plane).

Usage::

    # driver: ship paths, not bytes
    cluster.train([[FileManifest(p) for p in shard] for shard in shards])

    # node (map_fun): expand locally
    feed = ManifestFeed(ctx.get_data_feed())
    while not feed.should_stop():
        rows = feed.next_batch(batch_size)

When the files live on shared storage (NFS/GCS/HDFS-FUSE) every node
can read any manifest; with node-local storage, partition the manifests
to match file placement — the driver controls assignment either way.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Sequence

__all__ = [
    "FileManifest",
    "ManifestFeed",
    "consumed_records",
    "manifest_records",
    "merge_cursor_payloads",
    "plan_manifests",
    "read_manifest",
    "read_manifest_chunks",
    "remaining_manifest",
    "replan_manifests",
    "split_manifest",
    "stream_id",
]


@dataclasses.dataclass(frozen=True)
class FileManifest:
    """One node-readable unit of input: a file (or a record range of one).

    ``format``: ``'tfrecord'`` (rows decoded via the native codec +
    ``dfutil.fromTFExample``), ``'lines'`` (text lines, stripped), or
    ``'columnar'`` (a file of 64-aligned columnar frames written by
    ``feed.columnar.write_frames`` — read back as zero-copy column
    views over one shared mmap; ``ManifestFeed.batch_stream`` slices
    batches straight out of the chunks). Custom formats: pass a
    ``reader`` callable to :class:`ManifestFeed` instead.
    ``start``/``stop`` bound the record index range (Python slice
    semantics), so one large file can be split across nodes.
    """

    path: str
    format: str = "tfrecord"
    start: int = 0
    stop: int | None = None
    binary_features: tuple[str, ...] = ()
    # Training epoch this manifest instance belongs to (pull-mode
    # per-epoch shuffle): folded into :func:`stream_id`, so epoch 1's
    # re-read of the same records is a FRESH replay stream — consumed-
    # cursor state from epoch 0 can never suppress (or be suppressed
    # by) another epoch's pass. 0 keeps the legacy stream id exactly.
    epoch: int = 0


def read_manifest(
    m: FileManifest, reader: Callable[[FileManifest], Iterator[Any]] | None = None
) -> Iterator[Any]:
    """Yield the records a manifest names, reading the file locally."""
    if reader is not None:
        yield from _sliced(reader(m), m)
        return
    if m.format == "tfrecord":
        from tensorflowonspark_tpu.data import dfutil
        from tensorflowonspark_tpu.native.tfrecord import read_records

        # slice the SERIALIZED stream, decode only kept records: a node
        # taking the tail of a shared file must not pay proto decoding
        # for every record it skips
        for s in _sliced(read_records(m.path), m):
            yield dfutil.fromTFExample(s, list(m.binary_features))
    elif m.format == "lines":
        with open(m.path) as f:
            yield from _sliced((line.rstrip("\n") for line in f), m)
    elif m.format == "columnar":
        for chunk in read_manifest_chunks(m):
            yield from chunk.rows()
    else:
        raise ValueError(
            f"unknown manifest format {m.format!r}; use 'tfrecord', "
            "'lines', 'columnar', or pass reader= to ManifestFeed"
        )


def read_manifest_chunks(m: FileManifest, *, frame_cache=None):
    """ColumnChunks of a ``'columnar'`` manifest, honoring its
    ``start``/``stop`` record range by chunk-slicing (views — the mmap
    stays shared). ``frame_cache`` routes frame payload reads through
    the shared cache tier (see ``columnar.read_frames``)."""
    from tensorflowonspark_tpu.feed.columnar import read_frames

    pos = 0
    for chunk in read_frames(m.path, frame_cache=frame_cache):
        lo = max(m.start - pos, 0)
        hi = len(chunk) if m.stop is None else min(m.stop - pos, len(chunk))
        pos += len(chunk)
        if hi <= lo:
            if m.stop is not None and pos >= m.stop:
                return
            continue
        yield chunk if (lo, hi) == (0, len(chunk)) else chunk.view(lo, hi)


def plan_manifests(
    manifests: Sequence[FileManifest],
    num_shards: int,
    *,
    seed: int | None = None,
    epoch: int = 0,
    split: int = 1,
    reader: Callable[[FileManifest], Iterator[Any]] | None = None,
) -> list[list[FileManifest]]:
    """Deterministic round-robin shard assignment — the driver side of
    the pull plane's manifest planning (``TFCluster.assign_shards``).

    Round-robin (like ``TFCluster.train``'s partition assignment) keeps
    per-shard record statistics close to the input distribution when
    file sizes vary. Determinism is a replay requirement, not a
    nicety: an elastic reconfigure re-plans over the surviving roster,
    and a restarted driver must hand every node the same shard it held
    before, or the seeded replay cursors point at the wrong streams.
    Shards may be empty when ``len(manifests) < num_shards`` — a node
    with an empty shard sees an immediately-exhausted feed, not an
    error (skewed file counts are normal at small scale).

    **Per-epoch seeded shuffle** (ROADMAP 4a, the pull-mode
    ``reshuffle_each_iteration``): ``seed`` permutes the manifests with
    a PRNG keyed on ``(seed, epoch)`` — the SAME (seed, epoch) pair
    always reproduces the same plan byte-for-byte (what lets a
    restarted driver, an elastic re-plan, or a resumed run re-derive
    it), while each epoch draws a fresh permutation. ``split > 1``
    first splits every manifest into up to that many contiguous
    record-range pieces (:func:`split_manifest` — header-only for
    ``'columnar'``), making the shuffle block-granular rather than
    file-granular. The ``epoch`` is stamped onto every planned manifest
    and folded into its :func:`stream_id`, so record-exact replay
    cursors stay exact across epochs (resume mid-epoch is zero-dup/
    zero-gap — a cursor from epoch *e* speaks only for epoch *e*'s
    streams). ``seed=None`` with ``epoch > 0`` stamps the epoch without
    permuting.
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if split < 1:
        raise ValueError(f"split must be >= 1, got {split}")
    if epoch < 0:
        raise ValueError(f"epoch must be >= 0, got {epoch}")
    ms = list(manifests)
    if split > 1:
        ms = [
            piece
            for m in ms
            for piece in split_manifest(m, split, reader)
        ]
    if epoch and any(
        isinstance(m, FileManifest) and m.epoch != epoch for m in ms
    ):
        ms = [
            dataclasses.replace(m, epoch=int(epoch))
            if isinstance(m, FileManifest)
            else m
            for m in ms
        ]
    if seed is not None:
        import random

        # keyed on (seed, epoch): same pair -> same permutation on any
        # host/run (random.Random is version-stable for shuffle);
        # different epochs draw independent permutations
        rng = random.Random(1_000_003 * int(seed) + int(epoch))
        rng.shuffle(ms)
    return [ms[i::num_shards] for i in range(num_shards)]


def manifest_records(
    m: FileManifest,
    reader: Callable[[FileManifest], Iterator[Any]] | None = None,
) -> int:
    """Record count a manifest names. For ``'columnar'`` manifests this
    is a header-only frame scan (payload bytes untouched — splitting a
    multi-GB file costs one metadata pass); other formats pay a full
    read."""
    if reader is None and m.format == "columnar":
        from tensorflowonspark_tpu.feed.columnar import scan_frames

        total = sum(n for _, _, n in scan_frames(m.path))
        stop = total if m.stop is None else min(m.stop, total)
        return max(0, stop - min(m.start, stop))
    return sum(1 for _ in read_manifest(m, reader))


def split_manifest(
    m: FileManifest,
    n: int,
    reader: Callable[[FileManifest], Iterator[Any]] | None = None,
) -> list[FileManifest]:
    """Split one manifest into at most ``n`` contiguous record-range
    manifests (sizes differ by at most one; empties dropped) so a
    single large file can feed many nodes. Contiguous ranges keep each
    shard a sequential read of its region."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    total = manifest_records(m, reader)
    k, rem = divmod(total, n)
    out: list[FileManifest] = []
    lo = 0
    for i in range(n):
        hi = lo + k + (1 if i < rem else 0)
        if hi > lo:
            out.append(
                dataclasses.replace(m, start=m.start + lo, stop=m.start + hi)
            )
        lo = hi
    return out


def stream_id(m: Any) -> str:
    """Deterministic replay-stream id for one manifest: a pure function
    of WHAT is read (path + record range), never of when or by whom —
    a restarted reader, a relaunched node, an elastic re-plan, or the
    driver's shard re-planner all re-derive the same id, which is what
    lets consumed-cursor state and manifests be matched up across
    processes. A re-split's remaining manifest (advanced ``start``) is
    by construction a FRESH stream, and a manifest planned for a later
    ``epoch`` folds the epoch in (``#e<n>``) — each shuffled epoch's
    pass over the same records is its own stream, so cursor
    determinism composes with per-epoch reshuffling. Epoch 0 keeps the
    pre-shuffle id byte-identical (persisted cursors stay valid)."""
    if isinstance(m, FileManifest):
        stop = "" if m.stop is None else int(m.stop)
        sid = f"{m.path}@{int(m.start)}:{stop}"
        if m.epoch:
            sid += f"#e{int(m.epoch)}"
        return sid
    return f"manifest:{m!r}"


# ---------------------------------------------------------------------------
# live shard redistribution: re-planning over per-stream replay cursors
# (docs/ROBUSTNESS.md "Live shard redistribution"). The driver side of
# the handover protocol: given the manifests of the CURRENT plan and the
# union of published consumed-cursors, compute the manifests of the
# REMAINING records and deal them over the surviving workers.
# ---------------------------------------------------------------------------


def _columnar_block_lengths(m: FileManifest) -> list[int]:
    """Record count of each block a ``'columnar'`` manifest's reader
    yields, via header-only frame scans — the exact ``lo``/``hi``
    slicing of :func:`read_manifest_chunks` replayed over
    ``scan_frames`` counts, so block ordinal ``seq`` maps back to a
    record offset without touching payload bytes."""
    from tensorflowonspark_tpu.feed.columnar import scan_frames

    out: list[int] = []
    pos = 0
    for _off, _span, n in scan_frames(m.path):
        lo = max(m.start - pos, 0)
        hi = n if m.stop is None else min(m.stop - pos, n)
        pos += n
        if hi <= lo:
            if m.stop is not None and pos >= m.stop:
                break
            continue
        out.append(hi - lo)
    return out


def consumed_records(
    m: FileManifest,
    entry: Any,
    records_per_chunk: int = 1024,
    frame_blocks: bool | None = None,
) -> int:
    """Records of manifest ``m`` a replay-cursor entry proves consumed,
    counted from ``m.start``. ``entry`` is a
    :func:`~tensorflowonspark_tpu.feed.datafeed.normalize_cursor_entry`
    form (``seq`` or ``[seq, skip]``); ``None`` means nothing consumed.

    Block→record math depends on how the consumer read the manifest:
    ``'columnar'`` manifests (read without a custom reader) have
    frame-sliced blocks — resolved exactly via a header-only scan —
    while every other format streams ``records_per_chunk``-sized blocks
    (``data.readers.columnar_pieces``; the publisher's payload carries
    its value so both sides agree). Pass ``frame_blocks`` to override
    the format-based default (a custom ``reader=`` over a
    ``'columnar'``-format manifest uses chunk math).
    """
    if entry is None:
        return 0
    from tensorflowonspark_tpu.feed.datafeed import normalize_cursor_entry

    seq, skip = normalize_cursor_entry(entry)
    if seq < 0:
        return max(0, skip)
    if frame_blocks is None:
        frame_blocks = m.format == "columnar"
    if frame_blocks:
        lengths = _columnar_block_lengths(m)
        whole = sum(lengths[: seq + 1])
        partial = (
            min(skip, lengths[seq + 1]) if seq + 1 < len(lengths) else 0
        )
        return whole + partial
    # Fixed-size blocks: exact for every mid-stream block (only the tail
    # can be short, and a consumed tail means the stream is finished —
    # the overshoot then lands past the range and reads nothing).
    return (seq + 1) * int(records_per_chunk) + skip


def remaining_manifest(
    m: FileManifest,
    entry: Any,
    records_per_chunk: int = 1024,
    frame_blocks: bool | None = None,
    final: bool = False,
) -> FileManifest | None:
    """The manifest of ``m``'s UNCONSUMED records (``start`` advanced
    past the cursor's consumed prefix — a fresh replay stream), or
    ``None`` when nothing remains. ``final`` asserts full consumption
    regardless of the entry (an exhausted consumer's flag beats block
    math — for non-columnar formats the total is not knowable without
    a full read)."""
    if final:
        return None
    consumed = consumed_records(
        m, entry, records_per_chunk=records_per_chunk, frame_blocks=frame_blocks
    )
    if consumed <= 0:
        return m
    if m.format == "columnar" and (frame_blocks is None or frame_blocks):
        if consumed >= manifest_records(m):
            return None
    elif m.stop is not None and m.start + consumed >= m.stop:
        return None
    return dataclasses.replace(m, start=m.start + consumed)


def merge_cursor_payloads(
    payloads: Iterator[dict[str, Any]] | Sequence[dict[str, Any]],
) -> dict[str, dict[str, Any]]:
    """Union the per-node cursor publications into one per-stream view:
    ``{stream: {"entry", "records_per_chunk", "frame_blocks"}}``.

    Under any single plan each stream has one owner, but across plan
    generations (and across a crash, where the dead node's LAST
    publication and a survivor's re-read both speak for overlapping
    ranges) two payloads can claim the same stream — consumption claims
    are append-only truths, so the one covering more records wins
    (:func:`~tensorflowonspark_tpu.feed.datafeed.cursor_covers`)."""
    from tensorflowonspark_tpu.feed.datafeed import cursor_covers

    merged: dict[str, dict[str, Any]] = {}
    for p in payloads:
        rpc = int(p.get("records_per_chunk", 1024) or 1024)
        fb = p.get("frame_blocks")
        for s, entry in (p.get("cursor") or {}).items():
            s = str(s)
            prev = merged.get(s)
            if prev is None or cursor_covers(entry, prev["entry"]):
                merged[s] = {
                    "entry": entry,
                    "records_per_chunk": rpc,
                    "frame_blocks": fb,
                }
    return merged


def replan_manifests(
    shards: dict[int, Sequence[FileManifest]],
    merged_cursors: dict[str, dict[str, Any]],
    active_ids: Sequence[int],
    final_streams: Sequence[str] = (),
) -> dict[int, list[FileManifest]]:
    """THE re-split: deal the remaining records of a plan over the
    surviving workers.

    ``shards`` is the current plan (executor id → manifests; departed
    ids' shards included — their remainders are exactly what must be
    redistributed), ``merged_cursors`` the
    :func:`merge_cursor_payloads` union, ``final_streams`` the stream
    ids whose owners declared exhaustion (full consumption without
    block math). Returns a plan covering **every** active id (possibly
    with an empty shard) whose manifests partition the unconsumed
    records exactly — zero-gap and zero-dup by construction, because
    consumed prefixes are excluded and each remainder is assigned to
    exactly one worker. Deterministic: original (executor id, position)
    order in, round-robin over sorted active ids out."""
    if not active_ids:
        raise ValueError("cannot replan over an empty active worker set")
    finals = set(final_streams)
    remaining: list[FileManifest] = []
    for eid in sorted(shards):
        for m in shards[eid]:
            sid = stream_id(m)
            info = merged_cursors.get(sid)
            rm = remaining_manifest(
                m,
                None if info is None else info["entry"],
                records_per_chunk=(
                    1024 if info is None else info["records_per_chunk"]
                ),
                frame_blocks=None if info is None else info["frame_blocks"],
                final=sid in finals,
            )
            if rm is not None:
                remaining.append(rm)
    ids = sorted(int(i) for i in active_ids)
    dealt = plan_manifests(remaining, len(ids))
    return {eid: shard for eid, shard in zip(ids, dealt)}


def _sliced(rows: Iterator[Any], m: FileManifest) -> Iterator[Any]:
    import itertools

    if m.start or m.stop is not None:
        return itertools.islice(rows, m.start, m.stop)
    return rows


class ManifestFeed:
    """Expand driver-fed :class:`FileManifest` records into data records.

    Wraps a :class:`~tensorflowonspark_tpu.feed.datafeed.DataFeed`: each
    record pulled from the underlying feed must be a FileManifest (or
    whatever ``reader`` understands); its records stream out of
    :meth:`next_batch` without ever crossing the driver.
    ``should_stop`` matches DataFeed (false until the feed ends AND the
    last manifest is drained), so existing training loops work
    unchanged. One deliberate contract difference: batches fill across
    file AND partition/epoch boundaries (manifests are pulled one at a
    time, so DataFeed's partial-batch-at-EndPartition signal never
    fires here) — steady batch shapes are what jitted training wants.
    Callers needing strict epoch separation should make one ``train``
    + drain cycle per epoch instead of ``num_epochs > 1``.
    """

    def __init__(
        self,
        feed,
        reader: Callable[[FileManifest], Iterator[Any]] | None = None,
    ):
        self.feed = feed
        self.reader = reader
        self._iter: Iterator[Any] | None = None

    def should_stop(self) -> bool:
        return self._iter is None and self.feed.should_stop()

    def next_batch(self, batch_size: int) -> list[Any]:
        """Up to ``batch_size`` records; empty once the feed has ended
        and the last manifest is drained."""
        out: list[Any] = []
        while len(out) < batch_size:
            if self._iter is not None:
                try:
                    out.append(next(self._iter))
                    continue
                except StopIteration:
                    self._iter = None
            got = self.feed.next_batch(1)
            if not got:
                break  # EndOfFeed (DataFeed returns [] only then)
            self._iter = read_manifest(got[0], self.reader)
        return out

    def batch_stream(
        self,
        batch_size: int,
        multiple_of: int = 1,
        input_mapping: dict[str, str] | None = None,
    ):
        """Fixed-size batches, exactly like ``DataFeed.batch_stream``
        (steady jit shapes; the feed tail trims to ``multiple_of``).
        Manifest records are rows, so an ``input_mapping`` for column
        assembly is taken here rather than from the underlying feed
        (whose records are manifests, not rows)."""
        from tensorflowonspark_tpu.utils.batching import fixed_size_batches

        if input_mapping is not None:
            from tensorflowonspark_tpu.feed.columnar import column_batches

            # Columnar manifests contribute whole chunks (batches are
            # then SLICED column views); other formats contribute row
            # lists that pay columnize_rows per batch, as before.
            yield from column_batches(
                self._pieces(batch_size),
                batch_size,
                multiple_of,
                input_mapping,
            )
            return

        def records():
            while not self.should_stop():
                yield from self.next_batch(batch_size)

        yield from fixed_size_batches(
            records(), batch_size, multiple_of, assemble=lambda rows: list(rows)
        )

    def _pieces(self, batch_hint: int):
        """Pieces (ColumnChunk / row lists) across the fed manifests —
        starting with the remainder of a manifest a prior ``next_batch``
        call partially consumed (``self._iter``)."""
        import itertools

        def row_pieces(it):
            while True:
                rows = list(itertools.islice(it, max(batch_hint, 1)))
                if not rows:
                    return
                yield rows

        if self._iter is not None:
            leftover, self._iter = self._iter, None
            yield from row_pieces(leftover)
        while True:
            got = self.feed.next_batch(1)
            if not got:
                return
            m = got[0]
            if (
                self.reader is None
                and isinstance(m, FileManifest)
                and m.format == "columnar"
            ):
                yield from read_manifest_chunks(m)
                continue
            yield from row_pieces(read_manifest(m, self.reader))

    def terminate(self) -> None:
        self.feed.terminate()
