"""CLI over :mod:`tensorflowonspark_tpu.obs.trace_report`.

Summarize a ``jax.profiler`` trace directory in the terminal — per-lane
nesting-aware self-time tables plus the MXU/vector/copy/infeed/host
attribution breakdown — and optionally write the full report JSON::

    python -m tensorflowonspark_tpu.tools.trace_report /tmp/profile \
        [--top 30] [--lane TPU] [--json report.json]
"""

from tensorflowonspark_tpu.obs.trace_report import main

if __name__ == "__main__":
    raise SystemExit(main())
