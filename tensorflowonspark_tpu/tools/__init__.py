"""Standalone command-line tools (no user code required)."""
