"""Decode CLI over a Llama orbax checkpoint — no user Python needed.

The decode-side sibling of ``tools/run_model.py`` (which replays AOT
forward artifacts, the Scala-API parity path — SURVEY.md §2.2): load a
checkpointed Llama, read JSONL prompt rows, batch them with right-padding
+ per-row true lengths (``generate(prompt_lengths=...)``), sample with
greedy/top-k/top-p and optional EOS early stop, write JSONL completions
trimmed at each row's first EOS.

Prompts are token ids (``{"tokens": [1, 5, 9]}`` per line) — tokenizers
are corpus-specific and out of framework scope; pipe through one on
either side.

Usage::

    python -m tensorflowonspark_tpu.tools.generate_text \
        --checkpoint ckpt_dir/ --model tiny --prompts prompts.jsonl \
        --output out.jsonl [--max-new-tokens 64] [--eos-id N] \
        [--temperature 0.8 --top-k 40 --top-p 0.95] [--batch-size 8] \
        [--config-overrides '{"vocab_size": 1024}']

``--score`` switches from decoding to scoring: each row's per-token
next-token logprobs + summed total (the eval/perplexity surface; the
same scorer backs serve_model's /score endpoint). Composes with
``--mesh`` for models that need TP to fit.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="generate_text",
        description="KV-cache decode over a Llama orbax checkpoint",
    )
    p.add_argument(
        "--checkpoint",
        required=True,
        help="orbax dir: a CheckpointManager model dir (latest step is "
        "used; TrainState or bare param trees both work) or a "
        "save_checkpoint path",
    )
    p.add_argument("--model", choices=("tiny", "1b", "7b"), default="tiny")
    p.add_argument(
        "--config-overrides",
        default=None,
        help='JSON dict of LlamaConfig field overrides, e.g. '
        '\'{"vocab_size": 1024, "max_seq_len": 512}\'',
    )
    p.add_argument("--prompts", required=True, help="JSONL: {'tokens': [...]}")
    p.add_argument("--output", required=True, help="output JSONL path ('-' = stdout)")
    p.add_argument(
        "--score",
        action="store_true",
        help="score instead of decode: each input row's per-token "
        "next-token logprobs (+ summed total) as JSONL — the batch "
        "eval/perplexity surface (decode flags are ignored)",
    )
    p.add_argument(
        "--lora-scale",
        type=float,
        default=None,
        help="LoRA checkpoints: alpha/rank scale to re-apply after "
        "restore (the static scale field is not stored; default 1.0 "
        "matches add_lora's default alpha=rank)",
    )
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--min-p", type=float, default=None)
    p.add_argument("--eos-id", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--mesh",
        default=None,
        help="decode sharded over a device mesh, e.g. 'data=2,model=4' "
        "(TP weights on 'model', batch + KV caches on 'data'); "
        "--batch-size must be divisible by the 'data' extent",
    )
    p.add_argument(
        "--draft-checkpoint",
        default=None,
        help="speculative decoding: orbax checkpoint of a (smaller) "
        "draft model that proposes --spec-k tokens per target "
        "verification; greedy output is token-identical to the plain "
        "greedy decode, temperature>0 preserves the target's sampling "
        "distribution via the rejection rule. No --top-k/--top-p; "
        "composes with --mesh (TP/DP target, replicated draft)",
    )
    p.add_argument(
        "--draft-model", choices=("tiny", "1b", "7b"), default="tiny"
    )
    p.add_argument(
        "--draft-config-overrides",
        default=None,
        help="JSON LlamaConfig overrides for the draft model",
    )
    p.add_argument("--spec-k", type=int, default=4)
    return p


def _load_config(args):
    import dataclasses

    import jax.numpy as jnp

    from tensorflowonspark_tpu.models.llama import LlamaConfig

    base = {
        "tiny": LlamaConfig.tiny,
        "1b": LlamaConfig.llama_1b,
        "7b": LlamaConfig.llama2_7b,
    }[args.model]()
    if args.config_overrides:
        overrides = json.loads(args.config_overrides)
        if "dtype" in overrides:  # JSON carries it as a name string
            overrides["dtype"] = getattr(jnp, overrides["dtype"])
        if isinstance(overrides.get("rope_scaling"), dict):
            # JSON carries the RopeScaling dataclass as a dict
            # (import_hf_llama's --config-out emits it this way)
            from tensorflowonspark_tpu.models.llama import RopeScaling

            overrides["rope_scaling"] = RopeScaling(
                **overrides["rope_scaling"]
            )
        base = dataclasses.replace(base, **overrides)
    return base


def _load_params(checkpoint: str, cfg, lora_scale: "float | None" = None):
    """Restore params from either a CheckpointManager dir (latest step)
    or a bare save_checkpoint path; accept TrainState trees, {'state':
    ...} wrappers, or bare param trees. LoRA nodes (single adapters or
    multi-adapter banks) restored as plain dicts are rewrapped so the
    adapter paths route again (``ops/lora.py:rewrap_lora``);
    ``lora_scale`` re-supplies the non-stored static scale — None means
    the 1.0 default, resolved HERE so no caller can reintroduce the
    `or 1.0` falsy-zero bug (an explicit 0.0 disables the adapters)."""
    lora_scale = 1.0 if lora_scale is None else float(lora_scale)
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.compute.checkpoint import (
        CheckpointManager,
        restore_checkpoint,
    )

    with CheckpointManager(checkpoint) as mgr:
        step = mgr.latest_step()
        tree = mgr.restore(step) if step is not None else None
    if tree is None:
        tree = restore_checkpoint(checkpoint)
    for key in ("state", "params"):
        if isinstance(tree, dict) and key in tree:
            tree = tree[key]
    if isinstance(tree, dict) and "params" in tree:
        tree = tree["params"]
    if not (isinstance(tree, dict) and "embed" in tree):
        raise ValueError(
            f"checkpoint {checkpoint} does not contain a Llama param tree "
            f"(top-level keys: {sorted(tree) if isinstance(tree, dict) else type(tree)})"
        )
    from tensorflowonspark_tpu.ops.lora import rewrap_lora

    tree = rewrap_lora(tree, lora_scale)
    # decode in the model's compute dtype
    return jax.tree.map(
        lambda x: x.astype(cfg.dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


class PromptError(ValueError):
    """A problem with the CALLER's prompts (empty / longer than the
    decode width) — servers map this to a 4xx, unlike server-side
    configuration errors which stay plain ValueError/500."""


def decode_batches(
    model,
    params,
    prompts: list[list[int]],
    *,
    batch_size: int,
    width: int,
    max_new_tokens: int,
    rng,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    min_p: float | None = None,
    eos_id: int | None = None,
    uniform: bool = False,
    pad_to_batch: bool = False,
    mesh=None,
    draft=None,
    spec_k: int = 4,
):
    """Decode ``prompts`` at ONE static (batch_size, width) shape so the
    jitted prefill + decode loop compiles exactly once: short chunks pad
    rows by repeating the last prompt (results trimmed), short prompts
    right-pad to ``width`` (``generate``'s prompt_lengths path;
    ``uniform=True`` skips it when every prompt is exactly ``width``).
    Returns ``(completions, rng)`` with each completion trimmed at its
    first ``eos_id``. Shared by the CLI and serve_model's /generate.

    ``pad_to_batch``: always decode at exactly ``batch_size`` rows even
    when fewer prompts arrive (rows padded by repeating the last
    prompt). Servers MUST set this: the ``min()`` shortcut below would
    otherwise compile a fresh (n, width) program per distinct request
    size — seconds-to-minutes on the request thread — and thrash the
    compile cache, violating the one-static-shape bucketing policy.
    The one-shot CLI keeps the shortcut (smaller batch = less wasted
    compute, and its single compile is paid exactly once either way).

    ``mesh``: decode sharded over a device mesh (TP weights on 'model',
    batch + KV caches on 'data' — ``models.llama.generate``'s mesh
    path). The effective batch size must be divisible by the 'data'
    extent (set ``pad_to_batch`` so it stays the full ``batch_size``).

    ``draft``: a ``(draft_model, draft_params)`` pair switches decoding
    to speculative (``models.speculative``): the draft proposes
    ``spec_k`` tokens per target verification. At ``temperature == 0``
    output is token-identical to the plain greedy decode; at
    ``temperature > 0`` the rejection rule preserves the target's
    sampling distribution exactly. top_k/top_p do not combine with a
    draft. Composes with ``mesh`` (TP/DP target, replicated draft).
    """
    import jax
    import numpy as np

    from tensorflowonspark_tpu.models.llama import generate

    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    if draft is not None and (
        top_k is not None or top_p is not None or min_p is not None
    ):
        raise ValueError(
            "speculative decoding supports greedy (temperature 0) and "
            "plain-temperature sampling, not top_k/top_p/min_p "
            "truncation (truncation would change the distribution the "
            "rejection rule preserves)"
        )
    if not prompts:
        raise PromptError("no prompts given")
    bad = [i for i, p in enumerate(prompts) if not p or len(p) > width]
    if bad:
        raise PromptError(
            f"prompt rows {bad} are empty or exceed the decode width "
            f"({width})"
        )
    bsz = batch_size if pad_to_batch else min(batch_size, len(prompts))
    out: list[list[int]] = []
    for lo in range(0, len(prompts), bsz):
        chunk = prompts[lo : lo + bsz]
        n_real = len(chunk)
        chunk = chunk + [chunk[-1]] * (bsz - n_real)
        padded = np.zeros((bsz, width), np.int32)
        lengths = np.zeros(bsz, np.int32)
        for i, p in enumerate(chunk):
            padded[i, : len(p)] = p
            lengths[i] = len(p)
        rng, key = jax.random.split(rng)
        if draft is not None:
            from tensorflowonspark_tpu.models.speculative import (
                speculative_generate,
            )

            draft_model, draft_params = draft
            toks = np.asarray(
                speculative_generate(
                    model,
                    params,
                    draft_model,
                    draft_params,
                    jax.numpy.asarray(padded),
                    max_new_tokens=max_new_tokens,
                    k=spec_k,
                    eos_id=eos_id,
                    prompt_lengths=None if uniform else lengths,
                    mesh=mesh,
                    temperature=temperature,
                    rng=key,
                )
            )
        else:
            toks = np.asarray(
                generate(
                    model,
                    params,
                    jax.numpy.asarray(padded),
                    max_new_tokens=max_new_tokens,
                    temperature=temperature,
                    top_k=top_k,
                    top_p=top_p,
                    min_p=min_p,
                    rng=key,
                    eos_id=eos_id,
                    prompt_lengths=None if uniform else lengths,
                    mesh=mesh,
                )
            )
        for row in toks[:n_real]:
            row = row.tolist()
            if eos_id is not None and eos_id in row:
                row = row[: row.index(eos_id) + 1]
            out.append(row)
    return out, rng


def build_score_fn(model, params, width: int, bsz: int):
    """Build ``sequences -> per-token logprobs`` over a Llama — the
    eval-harness surface (perplexity / sequence scoring), shared by the
    CLI's ``--score`` and serve_model's ``/score`` so the two cannot
    diverge. One static (bsz, width) compile, rows right-padded; a pure
    forward (no KV cache). If ``params`` are mesh-sharded (device_put
    under ``llama_param_shardings``), the jitted forward runs SPMD
    against those placements."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def score(tokens):
        logits = model.apply({"params": params}, tokens[:, :-1])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tgt = tokens[:, 1:]
        return jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]

    def score_rows(rows: list[list[int]]) -> list[list[float]]:
        if not rows:
            raise PromptError("'sequences' must be a non-empty list")
        if len(rows) > bsz:
            raise PromptError(
                f"at most {bsz} sequences per request (the compiled "
                f"batch shape)"
            )
        vocab = model.cfg.vocab_size
        for r in rows:
            if len(r) < 2:
                raise PromptError(
                    "each sequence needs >= 2 tokens (scores are "
                    "next-token logprobs)"
                )
            if len(r) > width:
                raise PromptError(
                    f"sequence length {len(r)} exceeds the score "
                    f"width {width}"
                )
            bad = [t for t in r if not 0 <= t < vocab]
            if bad:
                # XLA clamps out-of-range gathers, which would return
                # plausible-looking but meaningless logprobs
                raise PromptError(
                    f"token ids {bad[:5]} outside the vocabulary "
                    f"[0, {vocab})"
                )
        arr = np.zeros((bsz, width), np.int32)
        for i, r in enumerate(rows):
            arr[i, : len(r)] = r
        lp = np.asarray(score(jnp.asarray(arr)))
        return [lp[i, : len(r) - 1].tolist() for i, r in enumerate(rows)]

    return score_rows


def _score_main(args, model, params, cfg, seqs) -> int:
    """--score: emit per-token next-token logprobs (and the summed
    sequence logprob) for each input row instead of decoding — the
    batch eval surface, the CLI twin of serve_model's /score."""
    width = min(max(len(s) for s in seqs), cfg.max_seq_len)
    score_rows = build_score_fn(
        model, params, width=width, bsz=args.batch_size
    )
    out = open(args.output, "w") if args.output != "-" else sys.stdout
    try:
        for i in range(0, len(seqs), args.batch_size):
            for row in score_rows(seqs[i : i + args.batch_size]):
                out.write(
                    json.dumps(
                        {"logprobs": row, "total": float(sum(row))}
                    )
                    + "\n"
                )
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    import jax

    from tensorflowonspark_tpu.models.llama import Llama

    if args.batch_size < 1:
        raise SystemExit("--batch-size must be >= 1")
    cfg = _load_config(args)
    model = Llama(cfg)
    params = _load_params(
        args.checkpoint, cfg,
        lora_scale=getattr(args, "lora_scale", None),
    )

    with open(args.prompts) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    prompts = [list(map(int, r["tokens"])) for r in rows]
    if not prompts:
        raise ValueError(f"no prompts in {args.prompts}")
    if args.score and args.draft_checkpoint:
        raise SystemExit(
            "--score is a plain forward; --draft-checkpoint "
            "(speculative decoding) does not apply"
        )
    width = max((len(p) for p in prompts), default=1)
    if not args.score and width + args.max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"longest prompt ({width}) + max_new_tokens "
            f"({args.max_new_tokens}) exceeds max_seq_len "
            f"({cfg.max_seq_len})"
        )

    mesh = None
    if args.mesh:
        from tensorflowonspark_tpu.compute.mesh import (
            make_mesh,
            parse_axis_spec,
        )
        from tensorflowonspark_tpu.models.llama import llama_param_shardings

        mesh = make_mesh(parse_axis_spec(args.mesh))
        # place the weights in their TP layout once, not per chunk
        params = jax.device_put(params, llama_param_shardings(params, mesh))

    if args.score:
        # after the mesh placement above: sharded params make the
        # scoring forward SPMD (the 7B-doesn't-fit-one-chip case)
        return _score_main(args, model, params, cfg, prompts)

    draft = None
    if args.draft_checkpoint:
        dcfg = _load_config(
            argparse.Namespace(
                model=args.draft_model,
                config_overrides=args.draft_config_overrides,
            )
        )
        draft_params = _load_params(args.draft_checkpoint, dcfg)
        if mesh is not None:
            from tensorflowonspark_tpu.compute import layout

            # replicate the draft once, not per chunk
            draft_params = jax.device_put(
                draft_params, layout.replicated(mesh)
            )
        draft = (Llama(dcfg), draft_params)

    completions, _ = decode_batches(
        model,
        params,
        prompts,
        batch_size=args.batch_size,
        width=width,
        max_new_tokens=args.max_new_tokens,
        rng=jax.random.PRNGKey(args.seed),
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        min_p=args.min_p,
        eos_id=args.eos_id,
        # uniform corpora skip the padded path's scatter writes
        uniform=all(len(p) == width for p in prompts),
        # sharded decode needs the batch divisible by the 'data' extent;
        # padding to the full batch keeps one shape that is
        pad_to_batch=mesh is not None,
        mesh=mesh,
        draft=draft,
        spec_k=args.spec_k,
    )
    out = open(args.output, "w") if args.output != "-" else sys.stdout
    try:
        for row in completions:
            out.write(json.dumps({"tokens": row}) + "\n")
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
