"""Import a Hugging Face Llama checkpoint into this framework.

The switching-cost killer: users of the reference ecosystem hold their
weights as HF `LlamaForCausalLM` checkpoints (config.json +
model.safetensors / pytorch_model*.bin). This tool maps them onto the
native :class:`~tensorflowonspark_tpu.models.llama.Llama` param tree
and writes an orbax checkpoint that every consumer here understands —
`generate`/`serve_model`/`generate_text` (incl. mesh-sharded and
speculative decode), `llama_fsdp` fine-tuning, LoRA, int8 quantization.

Layout mapping (verified logit-exact against the HF implementation in
``tests/test_hf_import.py``):

- torch ``nn.Linear`` stores ``(out, in)``; our kernels are
  ``(in, out)`` → every projection transposes.
- HF applies RoPE in the same half-split (rotate_half) convention as
  ``models/llama.py:rope`` with ``inv_freq = theta**(-2i/d)``, so Q/K
  need NO permutation.
- ``lm_head.weight (vocab, hidden)`` → ``lm_head (hidden, vocab)``
  (transpose); tied-embedding checkpoints (no lm_head key) tie to the
  embedding.
- RMSNorm weights map 1:1 (``scale``).

Usage::

    python -m tensorflowonspark_tpu.tools.import_hf_llama \
        --hf-dir /path/to/hf_checkpoint --output ckpt_dir \
        [--dtype bfloat16] [--config-out cfg.json]

``--config-out`` writes the matching LlamaConfig field overrides as
JSON, ready for the decode tools' ``--config-overrides``.
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import os
import sys


def load_hf_state_dict(hf_dir: str) -> dict:
    """Read every weight in an HF checkpoint dir into numpy, handling
    sharded safetensors and torch .bin files."""
    import numpy as np

    state: dict = {}
    st_files = sorted(glob.glob(os.path.join(hf_dir, "*.safetensors")))
    bin_files = sorted(glob.glob(os.path.join(hf_dir, "pytorch_model*.bin")))
    if st_files:
        from safetensors import safe_open

        for path in st_files:
            with safe_open(path, framework="np") as f:
                for key in f.keys():
                    state[key] = f.get_tensor(key)
    elif bin_files:
        import torch

        for path in bin_files:
            shard = torch.load(path, map_location="cpu", weights_only=True)
            for key, tensor in shard.items():
                # bf16 torch tensors have no direct numpy view; go via
                # fp32 per TENSOR (not per shard dict) so peak memory
                # stays one tensor, not one widened model copy
                state[key] = tensor.float().numpy()
                del tensor
            del shard
    else:
        raise FileNotFoundError(
            f"no *.safetensors or pytorch_model*.bin under {hf_dir}"
        )
    # bf16 safetensors arrive as ml_dtypes bfloat16 — fine downstream
    return {k: np.asarray(v) for k, v in state.items()}


def hf_config_to_llama(hf_cfg: dict):
    """Map HF LlamaConfig fields onto ours.

    Features this framework's Llama doesn't implement are REJECTED, not
    silently dropped — a conversion that succeeds must be logit-exact:
    unknown ``rope_scaling`` kinds change RoPE frequencies, and
    ``mlp_bias`` adds vectors the bias-free MLP has no slot for.
    ``attention_bias`` (explicit, or implied by ``model_type: qwen2``)
    maps to QKV bias vectors in :class:`QDense`.
    """
    from tensorflowonspark_tpu.models.llama import LlamaConfig, RopeScaling

    scaling = None
    rs = hf_cfg.get("rope_scaling")
    if rs:
        kind = rs.get("rope_type", rs.get("type"))
        if kind == "llama3":
            scaling = RopeScaling(
                kind="llama3",
                factor=float(rs["factor"]),
                low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
                high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
                original_max_seq_len=int(
                    rs.get(
                        "original_max_position_embeddings",
                        hf_cfg.get("max_position_embeddings", 8192),
                    )
                ),
            )
        elif kind == "linear":
            scaling = RopeScaling(kind="linear", factor=float(rs["factor"]))
        else:
            raise ValueError(
                f"rope_scaling type {kind!r} is not supported (llama3 "
                "and linear are); converting anyway would silently "
                "change the RoPE frequencies"
            )
    if hf_cfg.get("mlp_bias"):
        raise ValueError(
            "mlp_bias=true checkpoints are not supported: the native "
            "MLP kernels are bias-free and dropping the biases would "
            "silently change the logits"
        )
    model_type = hf_cfg.get("model_type", "llama")
    if hf_cfg.get("attention_bias") and model_type != "qwen2":
        # HF Llama's attention_bias puts a bias on o_proj TOO (unlike
        # Qwen2's QKV-only convention, which is what QDense models);
        # converting would drop it and silently change the logits.
        raise ValueError(
            "attention_bias=true Llama-architecture checkpoints are "
            "not supported (their o_proj bias has no slot here); only "
            "Qwen2's QKV-only biases are"
        )
    # Qwen2 carries QKV biases unconditionally (its config has no
    # usable attention_bias flag).
    attention_bias = model_type == "qwen2"
    # Qwen2 gates sliding_window behind use_sliding_window AND applies
    # it per-layer: layers below max_window_layers run FULL attention
    # (HF configuration_qwen2.py layer_types). A heterogeneous mix has
    # no representation here — reject rather than silently diverge.
    # CAUTION: config.json omits default-valued fields (to_diff_dict),
    # so the fallbacks must match HF's QWEN2 defaults
    # (use_sliding_window=False, max_window_layers=28) — a generic
    # truthy/zero fallback would window models HF runs full, or
    # globalize a per-layer mix it should reject.
    use_sw = bool(
        hf_cfg.get(
            "use_sliding_window", model_type != "qwen2"
        )
    )
    if use_sw and hf_cfg.get("sliding_window") is not None:
        n_layers = int(hf_cfg["num_hidden_layers"])
        mwl = int(
            hf_cfg.get(
                "max_window_layers", 28 if model_type == "qwen2" else 0
            )
        )
        if 0 < mwl < n_layers:
            raise ValueError(
                f"per-layer sliding window (max_window_layers={mwl} of "
                f"{n_layers}) is not supported; converting with a "
                "global window would silently change the logits"
            )
        if mwl >= n_layers:
            use_sw = False  # every layer is below the threshold: full
    return LlamaConfig(
        vocab_size=int(hf_cfg["vocab_size"]),
        hidden_size=int(hf_cfg["hidden_size"]),
        intermediate_size=int(hf_cfg["intermediate_size"]),
        num_layers=int(hf_cfg["num_hidden_layers"]),
        num_heads=int(hf_cfg["num_attention_heads"]),
        num_kv_heads=int(
            hf_cfg.get("num_key_value_heads", hf_cfg["num_attention_heads"])
        ),
        max_seq_len=int(hf_cfg.get("max_position_embeddings", 4096)),
        rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
        rope_scaling=scaling,
        rms_norm_eps=float(hf_cfg.get("rms_norm_eps", 1e-5)),
        attention_bias=attention_bias,
        # Mistral-family checkpoints: same tensor layout as Llama plus
        # sliding-window local attention (null in v0.2+ configs).
        # Qwen2 GATES its sliding_window field behind use_sliding_window
        # (default False — the field is 4096 but INERT); honoring the
        # raw field would silently window long contexts.
        sliding_window=(
            int(hf_cfg["sliding_window"])
            if hf_cfg.get("sliding_window") is not None and use_sw
            else None
        ),
    )


_PROJ = {
    "q_proj": "q_proj",
    "k_proj": "k_proj",
    "v_proj": "v_proj",
    "o_proj": "o_proj",
}
_MLP = {"gate_proj": "gate_proj", "up_proj": "up_proj", "down_proj": "down_proj"}


def hf_state_to_params(state: dict, cfg, dtype="float32") -> dict:
    """HF ``model.*`` keys → the flax param tree ``Llama`` expects.

    MUTATES ``state``: each tensor is popped as it is consumed, so peak
    memory is one tree plus one in-flight tensor rather than two full
    copies (a 7B fp32 tree is ~28 GB — doubling it OOMs typical hosts).
    Leftover weight keys after the mapping raise: an unconsumed tensor
    means the checkpoint carries something this mapping doesn't
    understand, and dropping it silently would break logit exactness.
    """
    import numpy as np

    def take(key):
        if key not in state:
            raise KeyError(
                f"HF checkpoint is missing {key!r} (have e.g. "
                f"{sorted(state)[:5]}...) — not a Llama checkpoint?"
            )
        return state.pop(key)

    def cast(x):
        return np.asarray(x, dtype=dtype)

    params: dict = {
        "embed": cast(take("model.embed_tokens.weight")),
        "final_norm": {"scale": cast(take("model.norm.weight"))},
    }
    if "lm_head.weight" in state:
        params["lm_head"] = cast(take("lm_head.weight").T)
    else:
        # tie_word_embeddings=True checkpoints carry no lm_head
        params["lm_head"] = cast(params["embed"].T)
    for i in range(cfg.num_layers):
        hf = f"model.layers.{i}"
        layer = {
            "attn_norm": {
                "scale": cast(take(f"{hf}.input_layernorm.weight"))
            },
            "mlp_norm": {
                "scale": cast(
                    take(f"{hf}.post_attention_layernorm.weight")
                )
            },
            "attn": {
                ours: {
                    "kernel": cast(
                        take(f"{hf}.self_attn.{theirs}.weight").T
                    ),
                    # Qwen2-family QKV bias (1-D, no transpose);
                    # o_proj never carries one
                    **(
                        {
                            "bias": cast(
                                take(f"{hf}.self_attn.{theirs}.bias")
                            )
                        }
                        if cfg.attention_bias and theirs != "o_proj"
                        else {}
                    ),
                }
                for theirs, ours in _PROJ.items()
            },
            "mlp": {
                ours: {"kernel": cast(take(f"{hf}.mlp.{theirs}.weight").T)}
                for theirs, ours in _MLP.items()
            },
        }
        params[f"layer{i}"] = layer
    leftover = [
        k for k in state
        if k.endswith(".weight") or k.endswith(".bias")
    ]
    if leftover:
        raise ValueError(
            f"HF checkpoint has {len(leftover)} unconsumed weight "
            f"tensors (e.g. {sorted(leftover)[:4]}); converting anyway "
            "would silently drop them"
        )
    return params


def convert(hf_dir: str, output: str, dtype: str = "float32"):
    """Full conversion: returns ``(LlamaConfig, params)`` and writes the
    orbax checkpoint to ``output``."""
    from tensorflowonspark_tpu.compute.checkpoint import save_checkpoint

    with open(os.path.join(hf_dir, "config.json")) as f:
        hf_cfg = json.load(f)
    model_type = hf_cfg.get("model_type", "llama")
    if model_type not in ("llama", "mistral", "qwen2"):
        # mistral shares the llama tensor layout exactly (sliding
        # window -> LlamaConfig.sliding_window); qwen2 adds QKV bias
        # vectors (-> attention_bias)
        raise ValueError(
            f"model_type {model_type!r} is not supported; this importer "
            "covers the Llama family (llama, mistral, qwen2)"
        )
    cfg = hf_config_to_llama(hf_cfg)
    state = load_hf_state_dict(hf_dir)
    params = hf_state_to_params(state, cfg, dtype=dtype)
    save_checkpoint(output, {"params": params})
    return cfg, params


def config_overrides_json(cfg) -> str:
    """The LlamaConfig as a ``--config-overrides`` JSON string."""
    return json.dumps(
        {
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "intermediate_size": cfg.intermediate_size,
            "num_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "num_kv_heads": cfg.num_kv_heads,
            "max_seq_len": cfg.max_seq_len,
            "rope_theta": cfg.rope_theta,
            "rms_norm_eps": cfg.rms_norm_eps,
            **(
                {"rope_scaling": dataclasses.asdict(cfg.rope_scaling)}
                if cfg.rope_scaling is not None
                else {}
            ),
            # non-default architecture flags MUST ride along: a decode
            # tool fed these overrides without them would build a model
            # whose param tree (no bias slots) or masking (no window)
            # doesn't match the converted checkpoint
            **(
                {"attention_bias": True} if cfg.attention_bias else {}
            ),
            **(
                {"sliding_window": cfg.sliding_window}
                if cfg.sliding_window is not None
                else {}
            ),
        }
    )


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="import_hf_llama",
        description="Convert a Hugging Face Llama checkpoint to an "
        "orbax param checkpoint for this framework",
    )
    p.add_argument("--hf-dir", required=True)
    p.add_argument("--output", required=True)
    p.add_argument(
        "--dtype",
        default="float32",
        choices=("float32", "bfloat16", "float16"),
        help="storage dtype for the converted weights",
    )
    p.add_argument(
        "--config-out",
        default=None,
        help="also write the matching LlamaConfig overrides JSON here "
        "(feed to the decode tools' --config-overrides)",
    )
    args = p.parse_args(argv)
    cfg, params = convert(args.hf_dir, args.output, dtype=args.dtype)
    import numpy as np

    n = sum(int(np.size(x)) for x in _leaves(params))
    print(
        f"converted {n / 1e6:.1f}M params "
        f"({cfg.num_layers}L/{cfg.hidden_size}h/{cfg.num_heads}a"
        f"/{cfg.num_kv_heads}kv) -> {args.output}"
    )
    if args.config_out:
        with open(args.config_out, "w") as f:
            f.write(config_overrides_json(cfg) + "\n")
        print(f"config overrides -> {args.config_out}")
    return 0


def _leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    else:
        yield tree


if __name__ == "__main__":
    sys.exit(main())
