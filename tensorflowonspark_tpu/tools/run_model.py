"""Batch inference CLI over an exported model — no user Python needed.

Reference parity: the Scala inference API (SURVEY.md §2.2,
``src/main/scala/com/yahoo/tensorflowonspark/TFModel.scala``): load a
self-describing exported model, map input columns to tensors, run batches,
write an output "DataFrame". Here the artifact is a
:func:`tensorflowonspark_tpu.api.export.export_model` directory and the
DataFrames are TFRecord files (or JSONL).

Usage::

    python -m tensorflowonspark_tpu.tools.run_model \
        --export-dir model/ --input records/ --output out/ \
        [--format tfrecord|jsonl] [--batch-size 64]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="run_model", description="AOT batch inference over TFRecords"
    )
    p.add_argument("--export-dir", required=True)
    p.add_argument("--input", required=True, help="TFRecord dir/glob or JSONL file")
    p.add_argument("--output", required=True, help="output dir (tfrecord) or file (jsonl)")
    p.add_argument("--format", choices=("tfrecord", "jsonl"), default="tfrecord")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument(
        "--binary-features",
        default="",
        help="comma-separated bytes columns to keep raw when reading TFRecords",
    )
    return p


def _read_rows(args) -> list[dict[str, Any]]:
    if args.format == "jsonl":
        with open(args.input) as f:
            return [json.loads(line) for line in f if line.strip()]
    from tensorflowonspark_tpu.data import dfutil

    binary = tuple(c for c in args.binary_features.split(",") if c)
    return list(dfutil.loadTFRecords(args.input, binary_features=binary))


def _to_jsonable(row: Any) -> Any:
    if isinstance(row, dict):
        return {k: _to_jsonable(v) for k, v in row.items()}
    if isinstance(row, np.ndarray):
        return row.tolist()
    if isinstance(row, (np.generic,)):
        return row.item()
    return row


def _write_rows(args, rows: list[Any]) -> None:
    if args.format == "jsonl":
        with open(args.output, "w") as f:
            for row in rows:
                f.write(json.dumps(_to_jsonable(row)) + "\n")
        return
    from tensorflowonspark_tpu.data import dfutil

    dict_rows = [
        row if isinstance(row, dict) else {"prediction": np.asarray(row)}
        for row in rows
    ]
    dfutil.saveAsTFRecords(dict_rows, args.output)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    from tensorflowonspark_tpu.api.export import load_model

    model = load_model(args.export_dir)
    rows = _read_rows(args)
    results = model.transform(rows, batch_size=args.batch_size)
    _write_rows(args, results)
    print(f"wrote {len(results)} predictions to {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
