"""HTTP inference server over an AOT export artifact.

Extends the no-user-code inference surface (reference parity: the Scala
``TFModel`` batch API — SURVEY.md §2.2 — covered for batch by
``tools/run_model``) to an online endpoint: load the artifact once, then
serve JSON predictions. stdlib-only (``http.server``), threaded, one
model instance shared across requests (jit-compiled call is thread-safe
to invoke).

Endpoints::

    GET  /healthz            -> {"status": "ok", "export_dir": ...}
    GET  /signature          -> the artifact's signature metadata
    POST /predict            -> body {"rows": [<row>, ...]}
                                (rows as dicts per input_mapping, or raw
                                arrays for single-input models)
                                -> {"predictions": [...]}

Usage::

    python -m tensorflowonspark_tpu.tools.serve_model \
        --export-dir /models/mnist [--port 8500] [--batch-size 64]
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from tensorflowonspark_tpu.tools.run_model import _to_jsonable

logger = logging.getLogger(__name__)


class _Handler(BaseHTTPRequestHandler):
    # set by make_server():
    model: Any = None
    export_dir: str = ""
    batch_size: int = 64
    # per-server lock (set in make_server): serializes jax dispatch on
    # one model while the HTTP layer stays threaded, so health checks
    # never queue behind a big batch
    predict_lock: threading.Lock

    def log_message(self, fmt, *fargs):  # route to logging, not stderr
        logger.info("%s " + fmt, self.client_address[0], *fargs)

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path == "/healthz":
            self._reply(200, {"status": "ok", "export_dir": self.export_dir})
        elif self.path == "/signature":
            self._reply(200, self.model.meta)
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        if self.path != "/predict":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            rows = payload["rows"]
            if not isinstance(rows, list) or not rows:
                raise ValueError("'rows' must be a non-empty list")
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            with self.predict_lock:
                preds = self.model.transform(
                    rows, batch_size=self.batch_size
                )
        except Exception as e:  # noqa: BLE001 - ferried to the client
            logger.exception("prediction failed")
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        # outside the try: a client hanging up mid-response must not be
        # logged as a prediction failure nor answered with a second reply
        self._reply(200, {"predictions": [_to_jsonable(p) for p in preds]})


def make_server(
    export_dir: str,
    port: int = 8500,
    batch_size: int = 64,
    host: str = "127.0.0.1",
) -> ThreadingHTTPServer:
    """Load the artifact and return a ready (unstarted) HTTP server;
    callers drive ``serve_forever``/``shutdown`` (tests bind port 0).
    Binds localhost by default — the endpoint is unauthenticated, so
    exposing it (``host='0.0.0.0'``) is an explicit operator choice."""
    from tensorflowonspark_tpu.api.export import load_model

    handler = type(
        "_BoundHandler",
        (_Handler,),
        {
            "model": load_model(export_dir),
            "export_dir": export_dir,
            "batch_size": batch_size,
            "predict_lock": threading.Lock(),  # per-server, not shared
        },
    )
    return ThreadingHTTPServer((host, port), handler)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="serve_model", description="HTTP inference over an AOT export"
    )
    p.add_argument("--export-dir", required=True)
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument(
        "--host",
        default="127.0.0.1",
        help="bind address (unauthenticated endpoint: exposing beyond "
        "localhost is an explicit choice)",
    )
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = make_server(
        args.export_dir, args.port, args.batch_size, host=args.host
    )
    logger.info(
        "serving %s on :%d", args.export_dir, server.server_address[1]
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
