"""HTTP inference server over an AOT export artifact.

Extends the no-user-code inference surface (reference parity: the Scala
``TFModel`` batch API — SURVEY.md §2.2 — covered for batch by
``tools/run_model``) to an online endpoint: load the artifact once, then
serve JSON predictions. stdlib-only (``http.server``), threaded, one
model instance shared across requests (jit-compiled call is thread-safe
to invoke).

Endpoints::

    GET  /healthz            -> {"status": "ok", "export_dir": ...}
    GET  /metrics            -> Prometheus text-format metrics (the
                                process registry + the continuous
                                engine's counters/gauges/histograms)
    GET  /stats              -> scheduler JSON incl. per-phase request
                                latency percentiles (queue/prefill/
                                dispatch/fetch/sweep) backed by obs
                                spans, plus the overlap pipeline's
                                pipeline_depth / inflight_depth /
                                drain_stalls / overlap_hidden_ms
    GET  /statusz            -> SLO burn-rate verdicts (multi-window)
                                + windowed-history stats + trace-ring
                                stats; pumps the telemetry window on
                                demand so pollers see fresh verdicts
    GET  /debugz/traces      -> tail-sampled request-trace ring stats
                                + retained trace ids
    GET  /debugz/trace/<id>  -> one retained request timeline as a
                                Chrome trace (merge with node traces
                                via tools/trace_merge.py). Requests
                                adopt an ``X-TFOS-Trace`` header (or
                                mint an id); every JSON reply — 429/
                                503/504 included — echoes ``trace``
    GET  /signature          -> the artifact's signature metadata
    POST /predict            -> body {"rows": [<row>, ...]}
                                (rows as dicts per input_mapping, or raw
                                arrays for single-input models)
                                -> {"predictions": [...]}
    POST /generate           -> body {"prompts": [[token ids], ...]}
                                -> {"completions": [[token ids], ...]}
                                (``--llama-checkpoint`` mode; decode
                                params are fixed server-side at startup
                                so the jitted decode compiles ONCE for
                                one static (batch, width) shape).
                                Continuous engine adds per-request
                                ``deadline_s``: budget expiry answers
                                504; a watchdog abort answers 503 +
                                Retry-After (docs/ROBUSTNESS.md)
    POST /score              -> body {"sequences": [[token ids], ...]}
                                -> {"logprobs": [[float, ...], ...]}
                                (per-token next-token logprobs — the
                                eval-harness surface; one static
                                compile, same bucketing as /generate)
    POST /v1/completions     -> OpenAI-completions-shaped alias over the
                                same engine (``--gen-engine continuous``
                                required: the translation always sets
                                max_tokens). Token ids only — ``prompt``
                                is [ids] or [[ids], ...]; text prompts
                                and string stops are a 400 (tokenizers
                                are corpus-specific, out of framework
                                scope). Response: the standard
                                text_completion envelope with
                                ``choices[].tokens`` carrying the ids
                                (``text`` is empty — no tokenizer),
                                per-token sampled logprobs under
                                ``choices[].logprobs.token_logprobs``
                                when ``logprobs`` >= 1, finish_reason
                                stop|length, and usage counts. Errors
                                keep this server's ``{"error": str}``
                                shape.
    GET  /v1/models          -> single-model list (``--served-model-name``)
    POST /admin/reload       -> authenticated weight hot-swap (token
                                from --admin-token-file or
                                TFOS_ADMIN_TOKEN; 403 without one):
                                body {"version", "path", "kind"} loads
                                a published orbax checkpoint and swaps
                                it into the live engine(s) between
                                decode blocks — synchronous for a
                                single engine, 202 + rolling update in
                                fleet mode. ``--rollout-channel DIR``
                                instead watches a publication channel
                                (docs/SERVING.md "Rolling weight
                                updates")

Usage::

    python -m tensorflowonspark_tpu.tools.serve_model \
        --export-dir /models/mnist [--port 8500] [--batch-size 64]
    python -m tensorflowonspark_tpu.tools.serve_model \
        --llama-checkpoint ckpt/ --model tiny [--gen-width 128] \
        [--max-new-tokens 64] [--eos-id N] [--temperature 0.8 ...]
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.obs import reqtrace
from tensorflowonspark_tpu.tools.run_model import _to_jsonable

logger = logging.getLogger(__name__)

# The server most recently started by main() — lets tooling and tests
# reach a CLI-started server (e.g. its ephemeral port under --port 0).
_last_server = None


class _Handler(BaseHTTPRequestHandler):
    # set by make_server():
    model: Any = None
    export_dir: str = ""
    batch_size: int = 64
    gen_fn: Any = None  # prompts -> completions (checkpoint mode)
    gen_batcher: Any = None  # _GenBatcher when --gen-batch-window > 0
    gen_engine: Any = None  # ContinuousBatcher (--gen-engine continuous)
    gen_max_new: int = 64  # per-request decode budget in engine mode
    score_fn: Any = None  # sequences -> per-token logprobs (/score)
    model_name: str = "default"  # /v1/models id + completion envelopes
    # zero-downtime weight rollout (docs/SERVING.md "Rolling weight
    # updates"): the RolloutController driving this server's engine(s),
    # and the shared secret gating POST /admin/reload (None = endpoint
    # disabled — hot-swapping weights is an operator-only surface)
    rollout_ctl: Any = None
    admin_token: str | None = None
    # request-level observability plane (docs/OBSERVABILITY.md):
    # the _ObsPlane pumping this server's registry into a windowed
    # History and evaluating SLO burn rates (/statusz); None = no
    # continuous engine to observe
    obs_plane: Any = None
    # the CURRENT request's trace id (adopted from X-TFOS-Trace or
    # minted at ingress); _reply stamps it into every JSON body so
    # error answers — 429/503/504 included — are trace-attributable
    _trace: str | None = None
    _last_code: int = 200
    # per-server lock (set in make_server): serializes jax dispatch on
    # one model while the HTTP layer stays threaded, so health checks
    # never queue behind a big batch
    predict_lock: threading.Lock

    def log_message(self, fmt, *fargs):  # route to logging, not stderr
        logger.info("%s " + fmt, self.client_address[0], *fargs)

    def _read_json_body(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def _reply(
        self, code: int, payload: dict, headers: dict | None = None
    ) -> None:
        if self._trace is not None and "trace" not in payload:
            payload = {**payload, "trace": self._trace}
        self._reply_text(
            code, json.dumps(payload), "application/json", headers
        )

    def _reply_text(
        self,
        code: int,
        text: str,
        content_type: str,
        headers: dict | None = None,
    ) -> None:
        self._last_code = code
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        self._trace = None
        if self.path in ("/healthz", "/readyz"):
            # Liveness vs readiness, SPLIT (docs/ROBUSTNESS.md "Serving
            # fleet"): live = the process/scheduler runs (restarting a
            # live server helps nobody); ready = route traffic here
            # (false during warmup and drain — a warmup stall must not
            # look wedged to a prober, and a draining server must fall
            # out of rotation without being killed). /healthz answers
            # 200 iff live, /readyz 200 iff ready; in fleet mode the
            # body carries the per-replica split too.
            h = {"live": True, "ready": True}
            if self.gen_engine is not None:
                try:
                    h = self.gen_engine.health()
                except Exception:  # noqa: BLE001 - a dead engine is a
                    # health verdict, not a 500
                    h = {"live": False, "ready": False}
            ok = h.get("live") if self.path == "/healthz" else h.get("ready")
            self._reply(
                200 if ok else 503,
                {
                    "status": "ok" if h.get("live") else "dead",
                    "export_dir": self.export_dir,
                    **h,
                },
            )
        elif self.path == "/signature" and self.model is not None:
            self._reply(200, self.model.meta)
        elif self.path == "/v1/models":
            # the OpenAI SDK's client.models.list() handshake — some
            # eval harnesses refuse to start without it
            self._reply(
                200,
                {
                    "object": "list",
                    "data": [
                        {
                            "id": self.model_name,
                            "object": "model",
                            "created": 0,
                            "owned_by": "tensorflowonspark_tpu",
                        }
                    ],
                },
            )
        elif self.path == "/metrics":
            # Prometheus text exposition: the process-global registry
            # (MetricsWriter mirrors, feed/train instrumentation) plus
            # the engine's per-instance registry when one is serving.
            from tensorflowonspark_tpu.obs import registry as obs_reg

            text = obs_reg.default_registry().render()
            if self.gen_engine is not None:
                text += self.gen_engine.metrics.render()
            self._reply_text(200, text, obs_reg.CONTENT_TYPE)
        elif self.path == "/stats":
            stats: dict = {"mode": "aot" if self.model is not None else ""}
            if self.gen_engine is not None:
                stats.update(
                    self.gen_engine.stats(),
                    mode=(
                        "fleet"
                        if getattr(self.gen_engine, "IS_FLEET", False)
                        else "continuous"
                    ),
                )
                if self.rollout_ctl is not None:
                    stats["rollout"] = self.rollout_ctl.stats()
            elif self.gen_batcher is not None:
                stats.update(
                    mode="coalesced",
                    decode_calls=self.gen_batcher.decode_calls,
                )
            elif self.gen_fn is not None:
                stats["mode"] = "fixed"
            self._reply(200, stats)
        elif self.path == "/statusz":
            # the SLO verdict surface: pump the windowed history NOW
            # (deterministic for pollers/tests — no waiting on the
            # background cadence) and report burn rates + breaches
            out: dict = {"export_dir": self.export_dir}
            if self.obs_plane is not None:
                try:
                    self.obs_plane.pump()
                    out.update(self.obs_plane.statusz())
                except Exception as e:  # noqa: BLE001 - a broken
                    # evaluator is a report, not a 500 — /statusz is
                    # what operators read DURING incidents
                    out["error"] = f"{type(e).__name__}: {e}"
            out["reqtrace"] = reqtrace.get_ring().stats()
            self._reply(200, out)
        elif self.path == "/debugz/traces":
            ring = reqtrace.get_ring()
            self._reply(200, {**ring.stats(), "trace_ids": ring.ids()})
        elif self.path.startswith("/debugz/trace/"):
            tid = self.path.rsplit("/", 1)[1]
            data = reqtrace.to_chrome(tid)
            if data is None:
                self._reply(
                    404,
                    {"error": f"no retained trace {tid!r} (unknown, "
                              "evicted, or not tail-sampled)"},
                )
            else:
                self._reply(200, data)
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        self._trace = None
        if self.path == "/generate":
            self._do_generate()
            return
        if self.path == "/admin/reload":
            self._do_admin_reload()
            return
        if self.path == "/v1/completions":
            self._do_v1_completions()
            return
        if self.path == "/score":
            self._do_score()
            return
        if self.path != "/predict":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        if self.model is None:
            self._reply(
                400, {"error": "server is in --llama-checkpoint mode; "
                      "POST /generate instead"}
            )
            return
        try:
            payload = self._read_json_body()
            rows = payload["rows"]
            if not isinstance(rows, list) or not rows:
                raise ValueError("'rows' must be a non-empty list")
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            with self.predict_lock:
                preds = self.model.transform(
                    rows, batch_size=self.batch_size
                )
        except Exception as e:  # noqa: BLE001 - ferried to the client
            logger.exception("prediction failed")
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        # outside the try: a client hanging up mid-response must not be
        # logged as a prediction failure nor answered with a second reply
        self._reply(200, {"predictions": [_to_jsonable(p) for p in preds]})

    def _do_admin_reload(self) -> None:
        """Authenticated hot weight swap (docs/SERVING.md "Rolling
        weight updates"). Body: ``{"version": ..., "path": <committed
        orbax checkpoint dir>, "kind": "full"|"lora", "step": N?}``.

        Single-engine mode answers SYNCHRONOUSLY once the swap,
        re-warm, and verification finished (this is the surface a
        fleet supervisor's ``SubprocessReplica.reload`` drives): 200
        on ``completed``, 409 on a shape/layout mismatch
        (``WeightsIncompatible`` — the caller triggers rollback), 500
        otherwise. Fleet mode (the router front-end) starts a rolling
        update in the background and answers 202 — rolling N replicas
        under drain is minutes, not an HTTP round trip."""
        import hmac

        if self.admin_token is None:
            self._reply(
                403,
                {"error": "admin endpoint disabled (no admin token "
                          "configured: set TFOS_ADMIN_TOKEN or "
                          "--admin-token-file)"},
            )
            return
        auth = self.headers.get("Authorization", "")
        token = (
            auth[len("Bearer "):]
            if auth.startswith("Bearer ")
            else self.headers.get("X-Admin-Token", "")
        )
        if not hmac.compare_digest(token, self.admin_token):
            self._reply(403, {"error": "invalid admin token"})
            return
        if self.rollout_ctl is None:
            self._reply(
                400,
                {"error": "/admin/reload requires --gen-engine "
                          "continuous"},
            )
            return
        from tensorflowonspark_tpu.serving.rollout import WeightsUpdate

        try:
            payload = self._read_json_body()
            update = WeightsUpdate(
                version=str(payload["version"]),
                kind=str(payload.get("kind") or "full"),
                path=str(payload["path"]),
                step=(
                    None
                    if payload.get("step") is None
                    else int(payload["step"])
                ),
            )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        # stamp the rollout onto every in-flight request's timeline:
        # a trace spanning the swap shows WHICH weights served it
        reqtrace.mark("admin.reload", version=update.version)
        ctl = self.rollout_ctl
        if getattr(self.gen_engine, "IS_FLEET", False):
            threading.Thread(
                target=ctl.roll, args=(update,), daemon=True,
                name="admin-rollout",
            ).start()
            self._reply(
                202,
                wire.encode(
                    "serve.reload", status="rolling",
                    version=update.version,
                ),
            )
            return
        t0 = time.monotonic()
        try:
            outcome = ctl.roll(update)
        except Exception as e:  # noqa: BLE001 - ferried to the caller
            logger.exception("admin reload crashed")
            self._reply(
                500,
                wire.encode(
                    "serve.error",
                    error=f"{type(e).__name__}: {e}",
                    error_type=type(e).__name__,
                ),
            )
            return
        if outcome == "completed":
            self._reply(
                200,
                wire.encode(
                    "serve.reload",
                    status="completed",
                    version=update.version,
                    swap_seconds=round(time.monotonic() - t0, 3),
                ),
            )
            return
        err = ctl.last_error or {}
        etype = err.get("type", "RolloutFailed")
        self._reply(
            409 if etype == "WeightsIncompatible" else 500,
            wire.encode(
                "serve.error",
                error=(
                    f"rollout {outcome}: "
                    f"{err.get('error', 'unknown failure')}"
                ),
                error_type=etype,
                outcome=outcome,
            ),
        )

    def _do_score(self) -> None:
        if self.score_fn is None:
            self._reply(
                400, {"error": "server was not started with "
                      "--llama-checkpoint; /score unavailable"}
            )
            return
        from tensorflowonspark_tpu.tools.generate_text import PromptError

        try:
            payload = self._read_json_body()
            seqs = payload["sequences"]
            if not isinstance(seqs, list):
                raise ValueError("'sequences' must be a list")
            seqs = [[int(t) for t in s] for s in seqs]
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            with self.predict_lock:
                logprobs = self.score_fn(seqs)
        except PromptError as e:
            self._reply(400, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 - server-side; log + 500
            logger.exception("scoring failed")
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(200, {"logprobs": logprobs})

    def _do_v1_completions(self) -> None:
        """OpenAI /v1/completions alias: translate the request into the
        native /generate schema and run the shared path, then wrap the
        result in the text_completion envelope."""
        try:
            raw = self._read_json_body()
            payload, meta = _openai_to_generate(raw, self.gen_max_new)
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        self._do_generate(payload=payload, v1_meta=meta)

    def _do_generate(self, payload=None, v1_meta=None) -> None:
        """Trace-owning ingress shell around :meth:`_generate_inner`:
        adopt the caller's ``X-TFOS-Trace`` id (a routed hop from a
        fleet parent — flagged ``propagated`` so the hop is always
        retrievable by the parent's tooling) or mint a fresh one, then
        stamp the terminal ``http.generate`` segment and finish the
        record with the HTTP outcome. Whoever BEGAN the trace finishes
        it — an in-process router/engine below us only appends."""
        hdr = self.headers.get(reqtrace.HEADER)
        tid, owned = reqtrace.ensure(hdr, route="http.generate")
        if tid is not None and hdr:
            reqtrace.flag(tid, propagated=True)
        self._trace = tid
        self._last_code = 200
        t0 = time.monotonic()
        try:
            self._generate_inner(payload, v1_meta, tid)
        except BaseException as e:
            reqtrace.flag(tid, error=type(e).__name__)
            if owned:
                reqtrace.finish(
                    tid, outcome="error", error=type(e).__name__
                )
            raise
        code = self._last_code
        reqtrace.segment(
            tid, "http.generate", time.monotonic() - t0
        )
        if code >= 400:
            reqtrace.flag(tid, http_error=code)
        if owned:
            reqtrace.finish(
                tid,
                outcome="ok" if code < 400 else "error",
                http_status=code,
            )

    def _generate_inner(self, payload=None, v1_meta=None, trace=None) -> None:
        if self.gen_fn is None and self.gen_engine is None:
            self._reply(
                400, {"error": "server was not started with "
                      "--llama-checkpoint; /generate unavailable"}
            )
            return
        try:
            if payload is None:
                payload = self._read_json_body()
            prompts = payload["prompts"]
            if not isinstance(prompts, list) or not prompts:
                raise ValueError("'prompts' must be a non-empty list")
            prompts = [[int(t) for t in p] for p in prompts]
            if any(not p for p in prompts):
                raise ValueError("prompts must be non-empty token lists")
            temperature = payload.get("temperature")
            max_new = payload.get("max_new_tokens")
            eos_id = payload.get("eos_id")
            adapter = payload.get("adapter")
            stop = payload.get("stop")
            n_samples = payload.get("n")
            req_top_k = payload.get("top_k")
            req_top_p = payload.get("top_p")
            req_seed = payload.get("seed")
            req_min_p = payload.get("min_p")
            req_fpen = payload.get("frequency_penalty")
            req_ppen = payload.get("presence_penalty")
            req_bias = payload.get("logit_bias")
            req_deadline = payload.get("deadline_s")
            want_logprobs = bool(payload.get("logprobs"))
            # rollout coherence surface: stamp each completion with the
            # weights version it resolved under (continuous engine only)
            want_versions = bool(payload.get("versions"))
            if (
                temperature is not None
                or max_new is not None
                or eos_id is not None
                or adapter is not None
                or stop is not None
                or n_samples is not None
                or req_top_k is not None
                or req_top_p is not None
                or req_seed is not None
                or req_min_p is not None
                or req_fpen is not None
                or req_ppen is not None
                or req_bias is not None
                or req_deadline is not None
                or want_logprobs
                or want_versions
            ) and self.gen_engine is None:
                raise ValueError(
                    "per-request temperature/max_new_tokens/eos_id/"
                    "adapter/stop/n/top_k/top_p/min_p/seed/penalties/"
                    "logprobs/deadline_s require --gen-engine "
                    "continuous (the fixed path bakes decode params "
                    "at startup)"
                )
            if temperature is not None:
                temperature = float(temperature)
            if max_new is not None:
                max_new = int(max_new)
                if not 1 <= max_new <= self.gen_max_new:
                    raise ValueError(
                        f"max_new_tokens must be in [1, "
                        f"{self.gen_max_new}] (the server's configured "
                        f"budget), got {max_new}"
                    )
            if eos_id is not None:
                eos_id = int(eos_id)
            if adapter is not None:
                adapter = int(adapter)
            if stop is not None:
                stop = [[int(t) for t in seq] for seq in stop]
            if req_top_k is not None:
                req_top_k = int(req_top_k)
            if req_top_p is not None:
                req_top_p = float(req_top_p)
            if req_seed is not None:
                req_seed = int(req_seed)
            if req_min_p is not None:
                req_min_p = float(req_min_p)
            if req_fpen is not None:
                req_fpen = float(req_fpen)
            if req_ppen is not None:
                req_ppen = float(req_ppen)
            if req_bias is not None:
                # OpenAI wire format: JSON object keys are strings
                req_bias = {
                    int(t): float(v) for t, v in dict(req_bias).items()
                }
            if req_deadline is not None:
                req_deadline = float(req_deadline)
            if n_samples is not None:
                n_samples = int(n_samples)
                if not 1 <= n_samples <= 16:
                    raise ValueError(
                        f"n must be in [1, 16], got {n_samples}"
                    )
                # EFFECTIVE temperature: the request value, else the
                # engine-wide default (--temperature); the engine
                # decodes any temp <= 0 greedily (_sample_rows selects
                # on temps > 0), which would return n identical rows
                eff_temp = (
                    temperature
                    if temperature is not None
                    else getattr(self.gen_engine, "_temperature", 0.0)
                )
                if n_samples > 1 and eff_temp <= 0:
                    raise ValueError(
                        "n > 1 with greedy decoding (effective "
                        "temperature <= 0) would return n identical "
                        "completions; set a temperature"
                    )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        from tensorflowonspark_tpu.tools.generate_text import PromptError

        stream = bool(payload.get("stream"))
        if stream and self.gen_engine is None:
            self._reply(
                400,
                {"error": "streaming requires --gen-engine continuous"},
            )
            return
        if stream and len(prompts) != 1:
            self._reply(
                400, {"error": "streaming supports exactly one prompt"}
            )
            return
        if stream and (n_samples or 1) > 1:
            self._reply(
                400,
                {"error": "streaming supports exactly one completion "
                          "(n must be 1)"},
            )
            return
        if stream:
            self._engine_stream(
                prompts[0], temperature, max_new, eos_id, want_logprobs,
                adapter, stop, req_top_k, req_top_p, req_seed,
                req_min_p, req_fpen, req_ppen, req_bias, req_deadline,
                trace=trace,
            )
            return
        from tensorflowonspark_tpu.serving import (
            DeadlineExceeded,
            EngineOverloaded,
            EngineWedged,
            FleetOverloaded,
            FleetUnavailable,
            ReplicaGone,
        )

        logprobs = None
        versions = None
        try:
            if self.gen_engine is not None:
                try:
                    n = n_samples or 1
                    fan = [p for p in prompts for _ in range(n)]
                    completions = self._engine_generate(
                        fan, temperature, max_new, eos_id,
                        want_logprobs, adapter, stop, req_top_k,
                        req_top_p, req_seed, req_min_p, req_fpen,
                        req_ppen, req_bias, req_deadline,
                        want_versions, trace=trace,
                    )
                    versions = None
                    if want_versions:
                        *rest, versions = completions
                        completions = (
                            rest[0] if len(rest) == 1 else tuple(rest)
                        )
                    if want_logprobs:
                        completions, logprobs = completions
                    if n > 1 and v1_meta is None:
                        # regroup: completions[i] becomes the LIST of n
                        # samples for prompt i (documented shape change;
                        # the OpenAI envelope keeps the flat order —
                        # prompt 0's n samples, then prompt 1's, ...)
                        completions = [
                            completions[i * n : (i + 1) * n]
                            for i in range(len(prompts))
                        ]
                        if logprobs is not None:
                            logprobs = [
                                logprobs[i * n : (i + 1) * n]
                                for i in range(len(prompts))
                            ]
                        if versions is not None:
                            versions = [
                                versions[i * n : (i + 1) * n]
                                for i in range(len(prompts))
                            ]
                except FleetOverloaded as e:
                    # router admission shed: the deadline cannot be met
                    # from queue-depth estimates (or every queue is
                    # full) — tell the client WHEN to come back, and
                    # WHERE the number came from (the router's
                    # queue-depth/EWMA estimate, not a fixed backoff)
                    self._reply(
                        429,
                        wire.encode(
                            "serve.error", error=str(e),
                            error_type="FleetOverloaded",
                            retry_after_src="router_estimate",
                        ),
                        {"Retry-After": str(int(math.ceil(e.retry_after)))},
                    )
                    return
                except FleetUnavailable as e:
                    # full-fleet drain / no ready replica
                    self._reply(
                        503,
                        wire.encode(
                            "serve.error", error=str(e),
                            error_type="FleetUnavailable",
                            retry_after_src="static",
                        ),
                        {"Retry-After": "2"},
                    )
                    return
                except EngineOverloaded as e:
                    self._reply(
                        503,
                        wire.encode(
                            "serve.error", error=str(e),
                            error_type="EngineOverloaded",
                            retry_after_src="static",
                        ),
                        {"Retry-After": "1"},
                    )
                    return
                except DeadlineExceeded as e:
                    # the documented degradation contract: an expired
                    # per-request budget is a gateway-timeout class
                    # outcome, not a server defect
                    self._reply(
                        504,
                        wire.encode(
                            "serve.error", error=str(e),
                            error_type="DeadlineExceeded",
                        ),
                    )
                    return
                except (EngineWedged, ReplicaGone) as e:
                    # the watchdog aborted in-flight work (or the
                    # replica died and failover was already spent) and
                    # the fleet/engine keeps serving — a retryable
                    # unavailability, not a generic 500
                    self._reply(
                        503,
                        wire.encode(
                            "serve.error", error=str(e),
                            error_type=type(e).__name__,
                            retry_after_src="static",
                        ),
                        {"Retry-After": "1"},
                    )
                    return
                except ValueError as e:
                    # the engine's submit-side prompt validation (width/
                    # budget) — client fault, like PromptError below; a
                    # ValueError from the OTHER paths stays a 500 (it
                    # would be a server-side defect, not bad input)
                    self._reply(400, {"error": str(e)})
                    return
            elif self.gen_batcher is not None:
                # coalesced path: the batcher's worker serializes the
                # decode (and takes predict_lock itself)
                completions = self.gen_batcher.submit(prompts)
            else:
                with self.predict_lock:
                    completions = self.gen_fn(prompts)
        except PromptError as e:  # the caller's prompts are at fault
            self._reply(400, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 - server-side; log + 500
            logger.exception("generation failed")
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if v1_meta is not None:
            eff_max = (
                max_new if max_new is not None else self.gen_max_new
            )
            choices = []
            for i, comp in enumerate(completions):
                ch = {
                    "index": i,
                    # token-id server: no tokenizer to render text with;
                    # the ids ride in "tokens" (clients detokenize)
                    "text": "",
                    "tokens": comp,
                    "logprobs": None,
                    "finish_reason": (
                        "stop" if len(comp) < eff_max else "length"
                    ),
                }
                if logprobs is not None:
                    ch["logprobs"] = {
                        "tokens": comp,
                        "token_logprobs": logprobs[i],
                        "top_logprobs": None,
                        "text_offset": None,
                    }
                choices.append(ch)
            import uuid

            self._reply(
                200,
                {
                    "id": f"cmpl-{uuid.uuid4().hex}",
                    "object": "text_completion",
                    "created": int(time.time()),
                    "model": v1_meta["model"] or self.model_name,
                    "choices": choices,
                    "usage": {
                        "prompt_tokens": sum(len(p) for p in prompts),
                        "completion_tokens": sum(
                            len(c) for c in completions
                        ),
                        "total_tokens": sum(len(p) for p in prompts)
                        + sum(len(c) for c in completions),
                    },
                },
            )
            return
        kw: dict[str, Any] = {"completions": completions}
        if logprobs is not None:
            kw["logprobs"] = logprobs
        if versions is not None:
            kw["weights_versions"] = versions
        self._reply(200, wire.encode("serve.completion", **kw))

    def _engine_stream(
        self,
        prompt,
        temperature=None,
        max_new=None,
        eos_id=None,
        want_logprobs=False,
        adapter=None,
        stop=None,
        top_k=None,
        top_p=None,
        seed=None,
        min_p=None,
        frequency_penalty=None,
        presence_penalty=None,
        logit_bias=None,
        deadline_s=None,
        trace=None,
    ) -> None:
        """Stream one completion as newline-delimited JSON: a
        ``{"token": t}`` line per decoded token (one engine step of
        latency each), then a ``{"done": true, "completion": [...]}``
        trailer. The response is close-delimited (no Content-Length);
        a mid-stream failure surfaces as an ``{"error": ...}`` line
        since the 200 status is already on the wire."""
        from tensorflowonspark_tpu.serving import (
            EngineOverloaded,
            FleetOverloaded,
            FleetUnavailable,
            ReplicaGone,
        )

        try:
            gen = self.gen_engine.stream(
                prompt,
                max_new or self.gen_max_new,
                temperature=temperature,
                eos_id=eos_id,
                yield_logprobs=want_logprobs,
                adapter=adapter,
                stop=stop,
                top_k=top_k,
                top_p=top_p,
                seed=seed,
                min_p=min_p,
                frequency_penalty=frequency_penalty,
                presence_penalty=presence_penalty,
                logit_bias=logit_bias,
                deadline_s=deadline_s,
                trace=trace,
            )
        except FleetOverloaded as e:
            self._reply(
                429,
                wire.encode(
                    "serve.error", error=str(e),
                    error_type="FleetOverloaded",
                    retry_after_src="router_estimate",
                ),
                {"Retry-After": str(int(math.ceil(e.retry_after)))},
            )
            return
        except (FleetUnavailable, ReplicaGone) as e:
            self._reply(
                503,
                wire.encode(
                    "serve.error", error=str(e),
                    error_type=type(e).__name__,
                    retry_after_src="static",
                ),
                {"Retry-After": "2"},
            )
            return
        except EngineOverloaded as e:
            self._reply(
                503,
                wire.encode(
                    "serve.error", error=str(e),
                    error_type="EngineOverloaded",
                    retry_after_src="static",
                ),
                {"Retry-After": "1"},
            )
            return
        except ValueError as e:  # submit-side prompt validation
            self._reply(400, {"error": str(e)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Connection", "close")
        self.end_headers()
        out: list = []
        lps: list = []
        try:
            for item in gen:
                if want_logprobs:
                    t, lp = item
                    lps.append(lp)
                    line = wire.encode(
                        "serve.stream_chunk", token=t, logprob=lp
                    )
                else:
                    t = item
                    line = wire.encode("serve.stream_chunk", token=t)
                out.append(t)
                self.wfile.write(json.dumps(line).encode() + b"\n")
                self.wfile.flush()
            # the engine's result is the stop-TRIMMED completion (the
            # streamed tokens include any matched stop suffix); fall
            # back to the raw tokens if the iterator wasn't exhausted
            final = gen.result if gen.result is not None else out
            tkw: dict[str, Any] = {"done": True, "completion": final}
            if trace is not None:
                tkw["trace"] = trace
            if want_logprobs:
                tkw["logprobs"] = (
                    gen.logprobs if gen.result is not None else lps
                )
            wv = getattr(gen, "weights_version", None)
            if wv is not None:
                tkw["weights_version"] = wv
            trailer = wire.encode("serve.stream_trailer", **tkw)
            self.wfile.write(json.dumps(trailer).encode() + b"\n")
        except (BrokenPipeError, ConnectionResetError):
            logger.info("stream client disconnected")
        except Exception as e:  # noqa: BLE001 - status already sent
            logger.exception("stream failed mid-decode")
            reqtrace.flag(trace, error=type(e).__name__)
            try:
                ekw: dict[str, Any] = {
                    "error": f"{type(e).__name__}: {e}",
                    # typed so a fleet router fronting THIS server
                    # can reconstruct the engine error
                    "error_type": type(e).__name__,
                }
                if trace is not None:
                    # the 200 is long gone: the error TRAILER is the
                    # only place the stream's trace id can ride
                    ekw["trace"] = trace
                err_line = wire.encode("serve.stream_error", **ekw)
                self.wfile.write(
                    json.dumps(err_line).encode() + b"\n"
                )
            except OSError:
                pass
        finally:
            # Deterministic cancel on client disconnect (the primary
            # case this exists for) — don't lean on refcount GC of
            # `gen` to free the slot for a dead consumer.
            gen.close()

    def _engine_generate(
        self,
        prompts,
        temperature=None,
        max_new=None,
        eos_id=None,
        want_logprobs=False,
        adapter=None,
        stop=None,
        top_k=None,
        top_p=None,
        seed=None,
        min_p=None,
        frequency_penalty=None,
        presence_penalty=None,
        logit_bias=None,
        deadline_s=None,
        want_versions=False,
        trace=None,
    ):
        """Continuous-batching path: the request's rows are admitted
        ATOMICALLY (all accepted, or a 400/503 before any decodes — a
        partial admission would burn slots on work the erroring client
        discards), then decode concurrently, interleaved with other
        requests' rows — no convoying."""
        return self.gen_engine.submit_many(
            prompts,
            max_new or self.gen_max_new,
            temperature=temperature,
            eos_id=eos_id,
            return_logprobs=want_logprobs,
            adapter=adapter,
            stop=stop,
            top_k=top_k,
            top_p=top_p,
            seed=seed,
            min_p=min_p,
            frequency_penalty=frequency_penalty,
            presence_penalty=presence_penalty,
            logit_bias=logit_bias,
            deadline_s=deadline_s,
            return_versions=want_versions,
            trace=trace,
        )


def _openai_to_generate(raw: Any, budget: int) -> tuple[dict, dict]:
    """Translate an OpenAI /v1/completions body into the native
    /generate schema (+ envelope metadata). Raises ValueError on
    malformed or unsupported fields; the caller replies 400.

    Token ids only: ``prompt`` is [ids] or [[ids], ...] and ``stop`` is
    [ids] or [[ids], ...] — text forms are rejected with an explanation
    (tokenizers are corpus-specific, out of framework scope; pipe
    through one client-side). ``max_tokens`` defaults to the OpenAI 16
    clamped to the server's decode ``budget`` (a request that omitted
    every optional field must not 400 on a small-budget server; an
    EXPLICIT over-budget or zero value still rides the existing [1, N]
    validation); ``temperature`` defaults to the OpenAI 1.0 (NOT the
    engine's startup default, which is typically greedy — a client that
    sent nothing must get OpenAI semantics). ``logprobs: N`` maps to
    the sampled token's logprob for any non-null N including 0 (top-N
    alternatives are not offered). ``echo``, ``suffix``, ``best_of``
    (beyond n) and ``stream`` are unsupported.
    """
    if not isinstance(raw, dict):
        raise ValueError("body must be a JSON object")
    if raw.get("echo"):
        raise ValueError("'echo' is not supported; POST /score for "
                         "prompt logprobs")
    if raw.get("suffix"):
        raise ValueError("'suffix' (insertion) is not supported")
    if raw.get("stream"):
        raise ValueError("'stream' is not supported on /v1/completions;"
                         " POST /generate with stream=true instead")
    n = raw.get("n")
    best_of = raw.get("best_of")
    if best_of is not None and best_of != (n or 1):
        raise ValueError("'best_of' beyond 'n' is not supported")

    def _token_rows(value, what):
        if isinstance(value, str) or (
            isinstance(value, list)
            and any(isinstance(v, str) for v in value)
        ):
            raise ValueError(
                f"text {what} need a tokenizer, which is corpus-"
                f"specific and out of framework scope; send token ids "
                f"([[int, ...]]) and detokenize client-side"
            )
        if not isinstance(value, list) or not value:
            raise ValueError(
                f"'{what}' must be a non-empty token-id list or a "
                f"list of them"
            )
        return (
            [list(r) for r in value]
            if isinstance(value[0], list)
            else [list(value)]
        )

    payload: dict = {"prompts": _token_rows(raw.get("prompt"), "prompts")}
    max_tokens = raw.get("max_tokens")
    payload["max_new_tokens"] = (
        min(16, budget) if max_tokens is None else int(max_tokens)
    )
    temp = raw.get("temperature")
    payload["temperature"] = 1.0 if temp is None else float(temp)
    for key in (
        "top_p",
        "seed",
        "frequency_penalty",
        "presence_penalty",
        "logit_bias",
        "n",
        # extensions shared with /generate (not OpenAI, but harmless)
        "eos_id",
        "adapter",
        "top_k",
        "min_p",
    ):
        if raw.get(key) is not None:
            payload[key] = raw[key]
    if raw.get("stop") is not None:
        payload["stop"] = _token_rows(raw["stop"], "stop sequences")
    if raw.get("logprobs") is not None:  # 0 is valid: sampled-token lp
        payload["logprobs"] = True
    return payload, {"model": raw.get("model")}


class _GenBatcher:
    """Coalesce concurrent /generate requests into shared decode calls.

    Decode throughput is batch-bound (the weight reads amortize over
    rows), but HTTP requests arrive one at a time; per-request decoding
    leaves the batch mostly padding. The batcher's worker thread takes
    the first queued request, lingers up to ``window`` seconds
    collecting more (up to ``max_rows`` prompt rows — the server's one
    compiled batch shape), runs ONE decode for all of them, and
    distributes per-request slices. A failing batch retries each
    request individually so one bad prompt cannot poison its
    co-batched neighbors.
    """

    _STOP = object()

    def __init__(self, gen_fn, lock, window: float, max_rows: int):
        import queue as _q

        self._gen_fn = gen_fn
        self._lock = lock
        self._window = float(window)
        self._max_rows = int(max_rows)
        self._queue: "_q.Queue" = _q.Queue()
        self._closed = False
        # Orders submit()'s closed-check-then-put against close()'s
        # set-flag-then-put-STOP, so no request can enqueue behind the
        # STOP marker (it would hang unanswered once the worker exits).
        self._submit_lock = threading.Lock()
        self.decode_calls = 0  # observability (asserted in tests)
        threading.Thread(
            target=self._worker, daemon=True, name="gen-batcher"
        ).start()

    def submit(self, prompts: list[list[int]]) -> list[list[int]]:
        slot: dict = {"event": threading.Event()}
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("server shutting down")
            self._queue.put((prompts, slot))
        slot["event"].wait()
        if "error" in slot:
            raise slot["error"]
        return slot["result"]

    def close(self) -> None:
        """Release the worker thread (and, with it, the model params
        its gen_fn closure pins) — the server calls this on shutdown.
        Requests racing the shutdown are failed, not left hanging: the
        worker drains the queue behind the _STOP and errors every slot,
        and submit() fails fast once the flag is up."""
        with self._submit_lock:
            self._closed = True
            self._queue.put(self._STOP)

    def _fail_pending(self) -> None:
        import queue as _q

        while True:
            try:
                item = self._queue.get_nowait()
            except _q.Empty:
                return
            if item is self._STOP:
                continue
            _, slot = item
            slot["error"] = RuntimeError("server shutting down")
            slot["event"].set()

    def _decode(self, prompts):
        self.decode_calls += 1
        with self._lock:
            return self._gen_fn(prompts)

    def _worker(self) -> None:
        import queue as _q

        pending = None
        while True:
            first = pending if pending is not None else self._queue.get()
            pending = None
            if first is self._STOP:
                self._fail_pending()
                return
            batch = [first]
            rows = len(first[0])
            deadline = time.monotonic() + self._window
            while rows < self._max_rows:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    item = self._queue.get(timeout=remaining)
                except _q.Empty:
                    break
                if item is self._STOP or rows + len(item[0]) > self._max_rows:
                    # capacity (or shutdown): carry into the next round
                    # rather than overshooting the one compiled batch
                    # shape into a second full-size decode
                    pending = item
                    break
                batch.append(item)
                rows += len(item[0])
            flat = [p for req, _ in batch for p in req]
            try:
                results = self._decode(flat)
            except Exception as e:  # noqa: BLE001
                from tensorflowonspark_tpu.tools.generate_text import (
                    PromptError,
                )

                if len(batch) > 1 and isinstance(e, PromptError):
                    # isolate the guilty request(s): PromptError is
                    # raised by cheap pre-decode validation, so
                    # per-request retry costs ~nothing and co-batched
                    # neighbors must not inherit a 400
                    for req, slot in batch:
                        try:
                            slot["result"] = self._decode(req)
                        except Exception as e_one:  # noqa: BLE001
                            slot["error"] = e_one
                        slot["event"].set()
                else:
                    # server-side fault: every retry is doomed — fail
                    # the whole batch at once
                    for _, slot in batch:
                        slot["error"] = e
                        slot["event"].set()
                continue
            i = 0
            for req, slot in batch:
                slot["result"] = results[i : i + len(req)]
                i += len(req)
                slot["event"].set()


def _parse_gen_mesh(gen: dict):
    """Build the --gen-mesh device mesh (or None) — one parser for the
    fixed-batch and continuous-engine paths so axis handling cannot
    diverge between them."""
    if not gen.get("mesh"):
        return None
    from tensorflowonspark_tpu.compute.mesh import (
        make_mesh,
        parse_axis_spec,
    )

    return make_mesh(parse_axis_spec(gen["mesh"]))


def _build_engine(gen: dict):
    """Build the continuous-batching engine for ``--gen-engine
    continuous``: one persistent slot-based decode loop instead of the
    fixed-batch gen_fn. Composes with ``--gen-mesh`` (TP on 'model';
    other axes replicate). Incompatible with the fixed-batch-only
    options (coalescing window, speculative draft) — reject at startup,
    not on the first request."""
    from tensorflowonspark_tpu.models.llama import Llama
    from tensorflowonspark_tpu.serving import ContinuousBatcher
    from tensorflowonspark_tpu.tools.generate_text import (
        _load_config,
        _load_params,
    )

    for bad, flag in (
        ("batch_window", "--gen-batch-window"),
        ("draft_checkpoint", "--draft-checkpoint"),
    ):
        if gen.get(bad):
            raise ValueError(
                f"--gen-engine continuous does not compose with {flag} "
                "(the engine schedules per token; those options belong "
                "to the fixed-batch path)"
            )
    cfg = _load_config(
        argparse.Namespace(
            model=gen["model"], config_overrides=gen.get("config_overrides")
        )
    )
    model = Llama(cfg)
    max_new = int(gen.get("max_new_tokens", 64))
    raw_widths = gen.get("widths")
    if raw_widths:
        # --gen-widths replaces --gen-width entirely; validate at
        # startup like every other shape parameter (a 0-width bucket
        # would start fine and then reject every request).
        try:
            widths = tuple(int(w) for w in str(raw_widths).split(","))
        except ValueError:
            raise ValueError(
                f"--gen-widths must be a CSV of integers, got "
                f"{raw_widths!r}"
            ) from None
        if not widths or any(w < 1 for w in widths):
            raise ValueError(
                f"--gen-widths buckets must be >= 1, got {raw_widths!r}"
            )
    else:
        widths = (int(gen.get("width", 128)),)
    if max(widths) + max_new > cfg.max_seq_len:
        raise ValueError(
            f"largest prompt-width bucket ({max(widths)}) + "
            f"--max-new-tokens ({max_new}) exceeds max_seq_len "
            f"({cfg.max_seq_len})"
        )
    mesh = _parse_gen_mesh(gen)
    if mesh is not None:
        # Duplicates ContinuousBatcher.__init__'s check so it fires in
        # milliseconds, BEFORE the (potentially multi-GB) restore below.
        tp = mesh.shape.get("model", 1)
        if cfg.num_heads % tp or cfg.num_kv_heads % tp:
            raise ValueError(
                f"heads ({cfg.num_heads}/{cfg.num_kv_heads} kv) not "
                f"divisible by the mesh 'model' extent {tp}"
            )
    max_queue = gen.get("max_queue")
    if max_queue is not None and int(max_queue) < 1:
        raise ValueError(
            f"--gen-max-queue must be >= 1, got {max_queue}"
        )
    # Cheap shape validation above happens BEFORE the (potentially
    # multi-GB) checkpoint restore, same policy as the draft path.
    params = _load_params(
        gen["checkpoint"], cfg, lora_scale=gen.get("lora_scale")
    )

    def _new_prefix_l2():
        # Fresh per engine (each facade owns a filler thread + client);
        # any construction failure degrades to L1-only — the cache tier
        # must never keep a replica from serving.
        addr = gen.get("cachetier_l2")
        if not addr or not gen.get("prefix_cache"):
            return None
        try:
            from tensorflowonspark_tpu.cachetier import (
                CacheClient,
                PrefixL2,
            )

            return PrefixL2(
                CacheClient(addr),
                chunk=int(gen.get("prefill_chunk") or 1),
                own_client=True,
            )
        except Exception:  # noqa: BLE001 - L2 is optional
            logger.warning("cachetier L2 attach failed", exc_info=True)
            return None

    def factory():
        # One engine per call: the fleet path respawns replicas through
        # this, so everything scheduler-stateful must be built fresh
        # here (model/params are shared read-only — jax arrays).
        return ContinuousBatcher(
            model,
            params,
            slots=int(gen.get("slots") or gen.get("batch_size", 8)),
            prompt_widths=widths,
            temperature=float(gen.get("temperature", 0.0)),
            top_k=gen.get("top_k"),
            top_p=gen.get("top_p"),
            min_p=gen.get("min_p"),
            eos_id=gen.get("eos_id"),
            seed=int(gen.get("seed", 0)),
            mesh=mesh,
            max_queue=gen.get("max_queue"),
            prefill_chunk=gen.get("prefill_chunk"),
            prefix_cache=gen.get("prefix_cache"),
            prefix_l2=_new_prefix_l2(),
            # `or 8` would map an EXPLICIT 0 to 8; only None (unset)
            # takes the default — explicit values pass through to the
            # engine's own max(1, ...) clamp, consistent with direct
            # construction.
            decode_block=(
                8 if gen.get("decode_block") is None
                else int(gen["decode_block"])
            ),
            pipeline_depth=(
                2 if gen.get("pipeline_depth") is None
                else int(gen["pipeline_depth"])
            ),
            watchdog_s=(
                None if gen.get("watchdog_s") is None
                else float(gen["watchdog_s"])
            ),
        )

    n_replicas = int(gen.get("replicas") or 1)
    if n_replicas > 1:
        # The fleet plane: N in-process replicas (each with its own
        # scheduler + watchdog) behind the health-routing FleetRouter —
        # the handler talks to the router exactly as it would to one
        # engine (docs/SERVING.md "Serving fleet").
        from tensorflowonspark_tpu.serving.fleet import ServingFleet
        from tensorflowonspark_tpu.serving.router import FleetRouter

        t0 = time.monotonic()
        fleet = ServingFleet(
            factory=factory,
            replicas=n_replicas,
            probe_interval=float(gen.get("probe_interval") or 1.0),
            warmup=bool(gen.get("warmup")),
        )
        router = FleetRouter(
            fleet,
            default_temperature=float(gen.get("temperature", 0.0)),
        )
        logger.info(
            "serving fleet of %d replicas ready in %.1fs",
            n_replicas,
            time.monotonic() - t0,
        )
        return router, max_new, model, params

    engine = factory()
    if gen.get("warmup"):
        t0 = time.monotonic()
        engine.warmup()
        logger.info(
            "engine warmup compiled all programs in %.1fs",
            time.monotonic() - t0,
        )
    return engine, max_new, model, engine._params


def _build_gen_fn(gen: dict):
    """Build ``prompts -> completions`` over a Llama checkpoint with ONE
    static decode shape: (gen_batch_size, gen_width). Requests are padded
    into that shape (rows repeat the last prompt, results trimmed), so
    the jitted prefill + decode loop compiles exactly once, at startup
    policy rather than per request — the bucketing discipline every
    static-shape serving stack uses. Returns ``(gen_fn, batch_size)`` —
    the batch size actually compiled, so the request batcher's row cap
    cannot drift from it."""
    import jax

    from tensorflowonspark_tpu.models.llama import Llama
    from tensorflowonspark_tpu.tools.generate_text import (
        _load_config,
        _load_params,
        decode_batches,
    )

    if float(gen.get("temperature", 0.0)) == 0.0 and any(
        gen.get(k) is not None for k in ("top_k", "top_p", "min_p")
    ):
        # generate() raises the same error per call; surface it at
        # startup, BEFORE the (potentially multi-GB) checkpoint restore
        raise ValueError(
            "--top-k/--top-p/--min-p require --temperature > 0 "
            "(temperature 0 is greedy argmax, which would silently "
            "ignore them)"
        )
    cfg = _load_config(
        argparse.Namespace(
            model=gen["model"], config_overrides=gen.get("config_overrides")
        )
    )
    model = Llama(cfg)
    params = _load_params(
        gen["checkpoint"], cfg, lora_scale=gen.get("lora_scale")
    )
    width = int(gen.get("width", 128))
    bsz = int(gen.get("batch_size", 8))
    max_new = int(gen.get("max_new_tokens", 64))
    if bsz < 1:
        raise ValueError(f"--gen-batch-size must be >= 1, got {bsz}")
    if width + max_new > cfg.max_seq_len:
        raise ValueError(
            f"--gen-width ({width}) + --max-new-tokens ({max_new}) "
            f"exceeds max_seq_len ({cfg.max_seq_len})"
        )
    rng_box = [jax.random.PRNGKey(int(gen.get("seed", 0)))]
    draft = None
    if gen.get("draft_checkpoint"):
        # fail at startup, not on the first request — and BEFORE the
        # (potentially multi-GB) draft checkpoint restore
        spec_k = int(gen.get("spec_k", 4))
        if spec_k < 1:
            raise ValueError(f"--spec-k must be >= 1, got {spec_k}")
        if (
            gen.get("top_k") is not None
            or gen.get("top_p") is not None
            or gen.get("min_p") is not None
        ):
            raise ValueError(
                "--draft-checkpoint supports greedy and plain-"
                "temperature sampling; drop --top-k/--top-p/--min-p "
                "(truncation would change the distribution the "
                "rejection rule preserves)"
            )
        dcfg = _load_config(
            argparse.Namespace(
                model=gen.get("draft_model", "tiny"),
                config_overrides=gen.get("draft_config_overrides"),
            )
        )
        # speculative needs k slots of verify-window headroom in BOTH
        # models' caches (speculative_generate re-checks per call; this
        # makes a doomed configuration fail before serving starts)
        for nm, c in (("--model", cfg), ("--draft-model", dcfg)):
            if width + max_new + spec_k > c.max_seq_len:
                raise ValueError(
                    f"--gen-width ({width}) + --max-new-tokens "
                    f"({max_new}) + --spec-k ({spec_k}) exceeds {nm}'s "
                    f"max_seq_len ({c.max_seq_len})"
                )
        draft = (
            Llama(dcfg),
            _load_params(gen["draft_checkpoint"], dcfg),
        )
    mesh = _parse_gen_mesh(gen)
    if mesh is not None:
        if bsz % mesh.shape["data"]:
            raise ValueError(
                f"--gen-batch-size ({bsz}) must be divisible by the "
                f"mesh 'data' extent ({mesh.shape['data']})"
            )
        from tensorflowonspark_tpu.compute import layout
        from tensorflowonspark_tpu.models.llama import llama_param_shardings

        # Pre-place the weights in their layouts ONCE at startup (target
        # TP-sharded, draft replicated): the decode path's per-call
        # device_put is then the no-op it assumes, instead of a full
        # weight reshard/broadcast on every request.
        params = jax.device_put(params, llama_param_shardings(params, mesh))
        if draft is not None:
            draft = (
                draft[0],
                jax.device_put(draft[1], layout.replicated(mesh)),
            )

    def gen_fn(prompts: list[list[int]]) -> list[list[int]]:
        out, rng_box[0] = decode_batches(
            model,
            params,
            prompts,
            batch_size=bsz,
            mesh=mesh,
            draft=draft,
            spec_k=int(gen.get("spec_k", 4)),
            # server mode: one (gen_batch_size, width) shape EVER
            # compiles — per-request sizes must not each compile
            pad_to_batch=True,
            width=width,
            max_new_tokens=max_new,
            rng=rng_box[0],
            temperature=float(gen.get("temperature", 0.0)),
            top_k=gen.get("top_k"),
            top_p=gen.get("top_p"),
            min_p=gen.get("min_p"),
            eos_id=gen.get("eos_id"),
        )
        return out

    return gen_fn, bsz, model, params


class _ObsPlane:
    """The serving process's windowed-telemetry + SLO plane: ONE
    History pumping ONE registry (``Registry.window()`` deltas are
    stateful, so the registry gets exactly one pumping consumer), and
    an :class:`~tensorflowonspark_tpu.obs.slo.SLOEvaluator` reading
    burn rates off it. A background thread pumps on ``interval`` so
    ``slo_burn_rate`` stays current between requests; ``/statusz``
    additionally pumps on demand so pollers see fresh verdicts."""

    def __init__(self, registry, slos, interval: float = 5.0):
        from tensorflowonspark_tpu.obs import History, SLOEvaluator

        self.registry = registry
        self.history = History(source="serve_model")
        self.evaluator = SLOEvaluator(slos, self.history, registry=registry)
        self.interval = float(interval)
        self._pump_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def pump(self):
        """One scrape + evaluation; serialized (the background cadence
        and /statusz share the registry's single delta window)."""
        with self._pump_lock:
            self.history.scrape_registry(self.registry)
            return self.evaluator.evaluate()

    def statusz(self) -> dict:
        return {
            "slo": self.evaluator.statusz(),
            "history": self.history.stats(),
        }

    def start(self) -> None:
        if self.interval <= 0 or self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.pump()
                except Exception as e:  # noqa: BLE001 - keep pumping
                    logger.warning("obs pump failed: %s", e)

        self._thread = threading.Thread(
            target=loop, daemon=True, name="obs-pump"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


class _Server(ThreadingHTTPServer):
    """ThreadingHTTPServer that also releases the request batcher's
    worker thread (and the params its closure pins) on shutdown."""

    gen_batcher = None
    gen_engine = None
    rollout_ctl = None
    obs_plane = None
    drain_on_shutdown = False

    def shutdown(self) -> None:
        super().shutdown()
        if self.obs_plane is not None:
            self.obs_plane.stop()
        if self.rollout_ctl is not None:
            # stop watching the channel BEFORE the engines go away —
            # a rollout racing teardown would hold seats of a closing
            # fleet
            self.rollout_ctl.stop()
        if self.gen_batcher is not None:
            self.gen_batcher.close()
        if self.gen_engine is not None:
            # drain: accepted requests finish before the engine stops
            # (--gen-drain-on-shutdown); default remains abrupt
            self.gen_engine.close(drain=self.drain_on_shutdown)


def make_server(
    export_dir: str | None,
    port: int = 8500,
    batch_size: int = 64,
    host: str = "127.0.0.1",
    gen: dict | None = None,
) -> ThreadingHTTPServer:
    """Load the artifact (and/or the ``gen`` Llama checkpoint config)
    and return a ready (unstarted) HTTP server; callers drive
    ``serve_forever``/``shutdown`` (tests bind port 0). Binds localhost
    by default — the endpoint is unauthenticated, so exposing it
    (``host='0.0.0.0'``) is an explicit operator choice."""
    model = None
    if export_dir is not None:
        from tensorflowonspark_tpu.api.export import load_model

        model = load_model(export_dir)
    gen_fn, gen_bsz = (None, 0)
    engine, engine_max_new = (None, 64)
    score_fn = None
    if gen is not None and gen.get("engine") == "continuous":
        engine, engine_max_new, lm, lm_params = _build_engine(gen)
    elif gen is not None:
        gen_fn, gen_bsz, lm, lm_params = _build_gen_fn(gen)
    if gen is not None:
        from tensorflowonspark_tpu.tools.generate_text import (
            build_score_fn,
        )

        # Score width must cover anything /generate can emit: the
        # LARGEST prompt bucket + the decode budget, capped at the
        # model's context (an over-long compile would score positions
        # the model was never shaped for).
        if gen.get("engine") == "continuous" and gen.get("widths"):
            max_bucket = max(
                int(w) for w in str(gen["widths"]).split(",")
            )
        else:
            max_bucket = int(gen.get("width", 128))
        score_fn = build_score_fn(
            lm,
            lm_params,
            width=min(
                max_bucket + int(gen.get("max_new_tokens", 64)),
                lm.cfg.max_seq_len,
            ),
            bsz=int(gen.get("batch_size", 8)),
        )
    lock = threading.Lock()  # per-server, not shared
    batcher = None
    window = float(gen.get("batch_window", 0.0) or 0.0) if gen else 0.0
    if gen_fn is not None and window > 0:
        batcher = _GenBatcher(gen_fn, lock, window, gen_bsz)
    rollout_ctl = None
    if engine is not None:
        # Zero-downtime weight rollout plane (docs/SERVING.md "Rolling
        # weight updates"): a controller always fronts the continuous
        # engine(s) — /admin/reload drives it directly, and
        # --rollout-channel additionally starts the channel watcher.
        # Construction is cheap: no threads until start().
        from tensorflowonspark_tpu.serving.rollout import (
            RolloutController,
            checkpoint_loader,
        )

        rollout_ctl = RolloutController(
            engine.fleet
            if getattr(engine, "IS_FLEET", False)
            else engine,
            channel_dir=gen.get("rollout_channel"),
            loader=checkpoint_loader(lm_params),
            poll_interval=float(gen.get("rollout_poll") or 2.0),
        )
        if gen.get("rollout_channel"):
            rollout_ctl.start()
    obs_plane = None
    if engine is not None:
        # SLO burn-rate plane over the engine's (or, in fleet mode,
        # the router's) registry — /statusz reads it, and the gauges
        # land in the same registry /metrics already renders
        from tensorflowonspark_tpu.obs.slo import (
            default_serving_slos,
            router_slos,
        )

        if getattr(engine, "IS_FLEET", False):
            slos = router_slos(
                latency_objective_s=float(
                    gen.get("slo_latency_s") or 30.0
                ),
                shed_budget=float(gen.get("slo_error_budget") or 0.02),
            )
            obs_registry = engine.fleet.metrics
        else:
            slos = default_serving_slos(
                ttft_objective_s=float(gen.get("slo_ttft_s") or 2.5),
                error_budget=float(gen.get("slo_error_budget") or 0.02),
            )
            obs_registry = engine.metrics
        obs_plane = _ObsPlane(
            obs_registry,
            slos,
            interval=float(gen.get("obs_window_s") or 5.0),
        )
        obs_plane.start()
    handler = type(
        "_BoundHandler",
        (_Handler,),
        {
            "model": model,
            "export_dir": export_dir or "",
            "batch_size": batch_size,
            # staticmethod: a bare function class attribute would bind
            # as a method and receive the handler as its first argument
            "gen_fn": staticmethod(gen_fn) if gen_fn is not None else None,
            "gen_batcher": batcher,
            "gen_engine": engine,
            "gen_max_new": engine_max_new,
            "score_fn": staticmethod(score_fn)
            if score_fn is not None
            else None,
            "model_name": (
                str(gen.get("served_model_name") or "default")
                if gen
                else "default"
            ),
            "rollout_ctl": rollout_ctl,
            "admin_token": (
                gen.get("admin_token") if gen else None
            ),
            "obs_plane": obs_plane,
            "predict_lock": lock,
        },
    )
    server = _Server((host, port), handler)
    server.gen_batcher = batcher
    server.gen_engine = engine
    server.rollout_ctl = rollout_ctl
    server.obs_plane = obs_plane
    server.drain_on_shutdown = bool(
        gen.get("drain_on_shutdown") if gen else False
    )
    return server


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="serve_model",
        description="HTTP inference over an AOT export and/or a Llama "
        "checkpoint (/generate)",
    )
    p.add_argument("--export-dir", default=None)
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument(
        "--host",
        default="127.0.0.1",
        help="bind address (unauthenticated endpoint: exposing beyond "
        "localhost is an explicit choice)",
    )
    p.add_argument("--llama-checkpoint", default=None)
    p.add_argument("--model", choices=("tiny", "1b", "7b"), default="tiny")
    p.add_argument("--config-overrides", default=None)
    p.add_argument("--gen-width", type=int, default=128)
    p.add_argument("--gen-batch-size", type=int, default=8)
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--min-p", type=float, default=None)
    p.add_argument("--eos-id", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--draft-checkpoint",
        default=None,
        help="speculative decoding for /generate: draft model "
        "checkpoint (greedy output identical to plain greedy; "
        "temperature>0 preserves the target's sampling distribution "
        "via the rejection rule); no --top-k/--top-p; composes with "
        "--gen-mesh (TP target, replicated draft)",
    )
    p.add_argument(
        "--draft-model", choices=("tiny", "1b", "7b"), default="tiny"
    )
    p.add_argument("--draft-config-overrides", default=None)
    p.add_argument("--spec-k", type=int, default=4)
    p.add_argument(
        "--gen-batch-window",
        type=float,
        default=0.0,
        help="coalesce concurrent /generate requests: linger this many "
        "seconds collecting requests into one shared decode batch (up "
        "to --gen-batch-size rows); 0 = decode per request. Decode "
        "cost is per-batch (weight reads amortize over rows), so under "
        "concurrent load a small window multiplies throughput",
    )
    p.add_argument(
        "--gen-mesh",
        default=None,
        help="shard /generate decoding over a device mesh, e.g. "
        "'data=2,model=4' (TP weights on 'model', batch + KV caches on "
        "'data'); --gen-batch-size must be divisible by the 'data' "
        "extent",
    )
    p.add_argument(
        "--gen-engine",
        choices=("fixed", "continuous"),
        default="fixed",
        help="'continuous' = slot-based continuous batching: requests "
        "join/leave a persistent decode loop at token granularity "
        "(no convoying behind a batch window); composes with "
        "--gen-mesh for TP serving (the 'model' axis; other axes only "
        "replicate) but not with "
        "--gen-batch-window/--draft-checkpoint",
    )
    p.add_argument(
        "--gen-slots",
        type=int,
        default=None,
        help="continuous engine KV-cache slots (default: "
        "--gen-batch-size)",
    )
    p.add_argument(
        "--gen-widths",
        default=None,
        help="continuous engine prompt-width buckets as a CSV (e.g. "
        "'32,128'): each prompt prefills at the smallest bucket that "
        "fits, one compilation per bucket (default: one bucket of "
        "--gen-width)",
    )
    p.add_argument(
        "--gen-max-queue",
        type=int,
        default=None,
        help="continuous engine: shed load with HTTP 503 once this "
        "many requests are waiting for a slot (default: unbounded)",
    )
    p.add_argument(
        "--gen-drain-on-shutdown",
        action="store_true",
        help="continuous engine: on server shutdown, finish accepted "
        "requests before stopping instead of failing them",
    )
    p.add_argument(
        "--served-model-name",
        default="default",
        help="model id reported by GET /v1/models and echoed in "
        "/v1/completions envelopes (OpenAI-compatible clients key on it)",
    )
    p.add_argument(
        "--gen-lora-scale",
        type=float,
        default=None,
        help="LoRA checkpoints: alpha/rank scale to re-apply after "
        "restore (orbax does not store the static scale field; "
        "default 1.0 matches add_lora's default alpha=rank)",
    )
    p.add_argument(
        "--gen-warmup",
        action="store_true",
        help="continuous engine: pre-compile every decode/prefill "
        "program at startup so the first real request's TTFT doesn't "
        "pay the XLA compiles",
    )
    p.add_argument(
        "--gen-prefix-cache",
        type=int,
        default=None,
        help="continuous engine: keep an LRU of this many prompt-prefix "
        "KV caches so requests sharing a prefix (system prompts, "
        "re-submits) resume prefill instead of recomputing it; each "
        "entry holds one full-length single-row KV cache in HBM. "
        "Requires --gen-prefill-chunk",
    )
    p.add_argument(
        "--cachetier-l2",
        default=None,
        metavar="HOST:PORT",
        help="continuous engine: attach the fleet-global prefix L2 at "
        "this cachetier daemon address (a ServingFleet in spawn mode "
        "injects it); requires --gen-prefix-cache. The service is an "
        "optimization, never a dependency — unreachable = L1-only",
    )
    p.add_argument(
        "--gen-decode-block",
        type=int,
        default=8,
        help="continuous engine: decode this many tokens per host "
        "scheduling iteration as one on-device lax.scan (fewer "
        "host round-trips per token); 1 = per-token scheduling "
        "(minimum admission-latency jitter)",
    )
    p.add_argument(
        "--gen-pipeline-depth",
        type=int,
        default=2,
        help="continuous engine: keep this many decode blocks in "
        "flight (dispatch-ahead software pipelining) so the host "
        "sweep/emit/stream cost hides behind device compute; 1 = the "
        "strictly serial dispatch->fetch->sweep loop (identical "
        "tokens either way; only latency/drain behavior differs)",
    )
    p.add_argument(
        "--gen-prefill-chunk",
        type=int,
        default=None,
        help="continuous engine: prefill prompts in chunks of this "
        "many tokens interleaved with decode steps, so a long "
        "admission doesn't stall live requests for its whole prefill "
        "(also skips the padding region: a short prompt costs "
        "ceil(len/chunk) chunks, not the full width bucket); default: "
        "whole-bucket prefill",
    )
    p.add_argument(
        "--gen-replicas",
        type=int,
        default=1,
        help="continuous engine: run this many engine replicas (each "
        "with its own scheduler/watchdog) behind a health-routing "
        "fleet router — prefix-aware placement, failover, draining, "
        "deadline-based load shedding (429/503). 1 = the single "
        "engine, no router",
    )
    p.add_argument(
        "--gen-probe-interval",
        type=float,
        default=1.0,
        help="fleet mode: replica health-probe cadence in seconds; an "
        "unhealthy replica flips to draining within miss_limit "
        "probes and is respawned",
    )
    p.add_argument(
        "--port-file",
        default=None,
        help="write the actually-bound port (useful with --port 0) to "
        "this file once the server is ready to accept requests — the "
        "spawn barrier fleet supervisors poll",
    )
    p.add_argument(
        "--admin-token-file",
        default=None,
        help="enable the authenticated POST /admin/reload weight "
        "hot-swap endpoint with the token read from this file "
        "(alternatively set TFOS_ADMIN_TOKEN — fleet supervisors "
        "inject it into subprocess replicas); without a token the "
        "endpoint answers 403",
    )
    p.add_argument(
        "--rollout-channel",
        default=None,
        help="continuous engine: watch this checkpoint publication "
        "channel directory (an atomically-written LATEST pointer at "
        "orbax step dirs; see serving/rollout.py) and hot-swap each "
        "newly published version into the live engine(s) — rolled one "
        "replica at a time under router health with --gen-replicas, "
        "with automatic rollback on failure",
    )
    p.add_argument(
        "--rollout-poll",
        type=float,
        default=2.0,
        help="rollout channel poll interval in seconds",
    )
    p.add_argument(
        "--slo-ttft-s",
        type=float,
        default=2.5,
        help="single-engine SLO: time-to-first-token objective in "
        "seconds (GET /statusz reports multi-window burn rates; "
        "breaches count in slo_breaches_total and dump the flight "
        "recorder)",
    )
    p.add_argument(
        "--slo-latency-s",
        type=float,
        default=30.0,
        help="fleet SLO (--gen-replicas > 1): end-to-end routed "
        "request latency objective in seconds",
    )
    p.add_argument(
        "--slo-error-budget",
        type=float,
        default=0.02,
        help="SLO error budget: allowed bad-request fraction (errors "
        "single-engine, admission sheds in fleet mode)",
    )
    p.add_argument(
        "--obs-window-s",
        type=float,
        default=5.0,
        help="windowed-telemetry pump cadence in seconds: each tick "
        "scrapes the serving registry into the bounded History rings "
        "and re-evaluates the SLO burn rates",
    )
    p.add_argument(
        "--gen-watchdog",
        type=float,
        default=None,
        help="continuous engine: abort in-flight requests (terminal "
        "EngineWedged) and keep serving when the scheduler makes no "
        "progress for this many seconds with work in flight — a "
        "wedged device transfer must not hang every caller forever. "
        "Use with --gen-warmup (first compiles look like stalls; "
        "warmup itself is exempt). Default: disabled",
    )
    args = p.parse_args(argv)
    if args.export_dir is None and args.llama_checkpoint is None:
        p.error("need --export-dir and/or --llama-checkpoint")
    if args.gen_replicas > 1 and args.gen_engine != "continuous":
        p.error(
            "--gen-replicas > 1 requires --gen-engine continuous "
            "(the fleet router fronts continuous engines)"
        )
    if args.gen_replicas < 1:
        p.error(f"--gen-replicas must be >= 1, got {args.gen_replicas}")
    if args.rollout_channel and args.gen_engine != "continuous":
        p.error(
            "--rollout-channel requires --gen-engine continuous "
            "(only the continuous engine hot-swaps weights)"
        )
    logging.basicConfig(level=logging.INFO)
    admin_token = None
    if args.admin_token_file:
        with open(args.admin_token_file, encoding="utf-8") as f:
            admin_token = f.read().strip() or None
    if admin_token is None:
        import os as _os

        admin_token = _os.environ.get("TFOS_ADMIN_TOKEN") or None
    gen = None
    if args.llama_checkpoint is not None:
        gen = dict(
            checkpoint=args.llama_checkpoint,
            model=args.model,
            config_overrides=args.config_overrides,
            width=args.gen_width,
            batch_size=args.gen_batch_size,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            min_p=args.min_p,
            eos_id=args.eos_id,
            seed=args.seed,
            mesh=args.gen_mesh,
            batch_window=args.gen_batch_window,
            draft_checkpoint=args.draft_checkpoint,
            draft_model=args.draft_model,
            draft_config_overrides=args.draft_config_overrides,
            spec_k=args.spec_k,
            engine=args.gen_engine,
            slots=args.gen_slots,
            widths=args.gen_widths,
            max_queue=args.gen_max_queue,
            prefill_chunk=args.gen_prefill_chunk,
            prefix_cache=args.gen_prefix_cache,
            cachetier_l2=args.cachetier_l2,
            decode_block=args.gen_decode_block,
            pipeline_depth=args.gen_pipeline_depth,
            watchdog_s=args.gen_watchdog,
            warmup=args.gen_warmup,
            lora_scale=args.gen_lora_scale,
            drain_on_shutdown=args.gen_drain_on_shutdown,
            served_model_name=args.served_model_name,
            replicas=args.gen_replicas,
            probe_interval=args.gen_probe_interval,
            admin_token=admin_token,
            rollout_channel=args.rollout_channel,
            rollout_poll=args.rollout_poll,
            slo_ttft_s=args.slo_ttft_s,
            slo_latency_s=args.slo_latency_s,
            slo_error_budget=args.slo_error_budget,
            obs_window_s=args.obs_window_s,
        )
    server = make_server(
        args.export_dir, args.port, args.batch_size, host=args.host, gen=gen
    )
    global _last_server  # drive/inspect a CLI-started server (tests,
    _last_server = server  # operator tooling; the bound port for --port 0)
    logger.info(
        "serving %s on :%d",
        args.export_dir or args.llama_checkpoint,
        server.server_address[1],
    )
    if args.port_file:
        # atomic (tmp + rename): a poller must never read a torn port.
        # Written AFTER make_server returns — the engine is built (and
        # warmed, with --gen-warmup), so the file doubles as the
        # replica spawn barrier.
        import os as _os

        tmp = f"{args.port_file}.tmp.{_os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(server.server_address[1]))
        _os.replace(tmp, args.port_file)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
