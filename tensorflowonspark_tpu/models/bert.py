"""BERT encoder family (BERT-base is the BASELINE.md text/estimator config).

Parity note: the reference had no transformer models of its own — its
"models" layer was the examples tree (SURVEY.md §2.4) and the estimator
pipeline (`tensorflowonspark/pipeline.py:TFEstimator`) was the API users
fine-tuned text models through. The rebuild's baseline names BERT-base
fine-tune via the estimator path; this file supplies that model natively.

TPU-first design notes:

- bf16 matmuls with fp32 LayerNorm and fp32 softmax (inside the shared
  attention op) — MXU-friendly without fp16-style loss-scaling.
- Bidirectional attention via the shared
  :func:`tensorflowonspark_tpu.ops.attention.dot_product_attention`.
  Padding is handled with ``segment_ids`` so batches keep static shapes
  under jit; note the shared op currently runs masked (padded) batches on
  the XLA path — the Pallas flash kernel kicks in for unpadded batches.
- ``bert_param_shardings``: Megatron rules — attention heads and FFN
  hidden over 'model' (TP), the complementary dim over 'fsdp'.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflowonspark_tpu.compute import layout

from tensorflowonspark_tpu.ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def bert_base(**kw) -> "BertConfig":
        return BertConfig(**kw)

    @staticmethod
    def bert_large(**kw) -> "BertConfig":
        return BertConfig(
            hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096, **kw
        )

    @staticmethod
    def tiny(**overrides) -> "BertConfig":
        base = dict(
            vocab_size=128,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            intermediate_size=128,
            max_seq_len=64,
        )
        base.update(overrides)
        return BertConfig(**base)


class _LayerNorm(nn.Module):
    eps: float

    @nn.compact
    def __call__(self, x):
        # fp32 statistics regardless of activation dtype.
        return nn.LayerNorm(epsilon=self.eps, dtype=jnp.float32)(x)


class EncoderBlock(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, segment_ids=None):
        cfg = self.config
        h = cfg.num_heads
        d = cfg.head_dim
        dense = lambda f, name: nn.Dense(f, dtype=cfg.dtype, name=name)

        # Post-LN (original BERT): attn -> add&norm -> ffn -> add&norm.
        q = dense(h * d, "query")(x).reshape(*x.shape[:2], h, d)
        k = dense(h * d, "key")(x).reshape(*x.shape[:2], h, d)
        v = dense(h * d, "value")(x).reshape(*x.shape[:2], h, d)
        attn = dot_product_attention(
            q, k, v, causal=False, segment_ids=segment_ids, impl=cfg.attention_impl
        )
        attn = dense(cfg.hidden_size, "attn_out")(attn.reshape(*x.shape))
        x = _LayerNorm(cfg.layer_norm_eps, name="attn_ln")(x + attn).astype(cfg.dtype)

        ffn = dense(cfg.intermediate_size, "ffn_in")(x)
        ffn = nn.gelu(ffn)
        ffn = dense(cfg.hidden_size, "ffn_out")(ffn)
        return _LayerNorm(cfg.layer_norm_eps, name="ffn_ln")(x + ffn).astype(cfg.dtype)


class Bert(nn.Module):
    """Returns (sequence_output [B,S,H], pooled_output [B,H])."""

    config: BertConfig

    @nn.compact
    def __call__(self, tokens, token_types=None, attention_mask=None):
        cfg = self.config
        B, S = tokens.shape
        if token_types is None:
            token_types = jnp.zeros_like(tokens)
        # The 0/1 padding mask is used directly as segment ids: attention
        # flows only between positions with EQUAL mask values, so real (1)
        # never attends to pad (0). Pad-pad attention is harmless — pad
        # positions are dropped by downstream masking/loss.
        segment_ids = attention_mask

        emb = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="tok")(
            tokens
        )
        emb += nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="typ"
        )(token_types)
        pos = self.param(
            "pos",
            nn.initializers.normal(0.02),
            (cfg.max_seq_len, cfg.hidden_size),
        )
        emb += pos[None, :S].astype(cfg.dtype)
        x = _LayerNorm(cfg.layer_norm_eps, name="emb_ln")(emb).astype(cfg.dtype)

        block = EncoderBlock
        if cfg.remat:
            block = nn.remat(EncoderBlock, static_argnums=())
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(x, segment_ids)

        pooled = nn.tanh(
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="pooler")(x[:, 0])
        )
        return x, pooled


class BertForClassification(nn.Module):
    config: BertConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, tokens, token_types=None, attention_mask=None):
        _, pooled = Bert(self.config, name="bert")(tokens, token_types, attention_mask)
        # Head in fp32 for a stable softmax.
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(pooled)


class BertForMLM(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, tokens, token_types=None, attention_mask=None):
        cfg = self.config
        seq, _ = Bert(cfg, name="bert")(tokens, token_types, attention_mask)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_transform")(seq)
        x = _LayerNorm(cfg.layer_norm_eps, name="mlm_ln")(nn.gelu(x)).astype(cfg.dtype)
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="mlm_head")(x)


def bert_param_shardings(params, mesh: Mesh):
    """Megatron-style rules keyed on param names (see module docstring)
    — the declarative 'bert' table in
    :mod:`tensorflowonspark_tpu.compute.layout`: a rule whose named
    dims don't divide the mesh extents falls through to the next."""
    return layout.param_shardings(params, mesh, "bert")


def classification_loss_fn(model: BertForClassification):
    """Build ``loss(params, batch)`` for batches
    {'tokens', 'label', optional 'mask'}."""
    import optax

    def loss(params, batch):
        logits = model.apply(
            {"params": params},
            batch["tokens"],
            attention_mask=batch.get("mask"),
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()

    return loss
