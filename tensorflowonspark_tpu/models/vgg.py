"""VGG (A/D variants — VGG-11/VGG-16) for the classic-zoo parity line.

Parity note: the reference's ``examples/slim`` tree vendored TF-slim's
nets (vgg/inception/resnet/lenet with a ``nets_factory``) — SURVEY.md
§2.4. VGG is the remaining classic family; from scratch in flax.

TPU-first design notes: NHWC, convs in bf16 (the 3x3 stacks are pure MXU
food), fp32 classifier head. BatchNorm instead of the original's
local-response-free plain convs — the standard modern training recipe —
so the same TrainState/batch_stats plumbing as ResNet/Inception applies.
The giant fc6/fc7 dense layers are kept (they are most of the 138M
params) but expressed as 1x1 convs on the pooled 7x7 map collapsed by
reshape — identical math, friendlier XLA layout.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflowonspark_tpu.ops.batch_norm import FusedBatchNorm


@dataclasses.dataclass(frozen=True)
class VGGConfig:
    # channels per conv stage; each stage ends in a 2x2 maxpool
    stage_sizes: tuple[int, ...] = (2, 2, 3, 3, 3)  # VGG-16 (variant D)
    num_classes: int = 1000
    width: int = 64
    fc_features: int = 4096
    dtype: jnp.dtype = jnp.bfloat16

    @staticmethod
    def vgg11(**kw) -> "VGGConfig":
        return VGGConfig(stage_sizes=(1, 1, 2, 2, 2), **kw)

    @staticmethod
    def vgg16(**kw) -> "VGGConfig":
        return VGGConfig(**kw)

    @staticmethod
    def tiny(**overrides) -> "VGGConfig":
        base = dict(
            stage_sizes=(1, 1), width=8, fc_features=32, num_classes=10
        )
        base.update(overrides)
        return VGGConfig(**base)


class VGG(nn.Module):
    config: VGGConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        x = x.astype(cfg.dtype)
        bn = 0  # running index pinning the pre-round-3 BatchNorm_N auto-names
        for stage, size in enumerate(cfg.stage_sizes):
            feats = cfg.width * 2 ** min(stage, 3)  # caps at 512 like the paper
            for _ in range(size):
                x = nn.Conv(
                    feats, (3, 3), padding="SAME", use_bias=False,
                    dtype=cfg.dtype,
                )(x)
                # fused-statistics BN — same profile rationale as
                # models/resnet.py:_ConvBN (ops/batch_norm.py)
                x = FusedBatchNorm(
                    momentum=0.9,
                    epsilon=1e-5,
                    dtype=cfg.dtype,
                    name=f"BatchNorm_{bn}",
                )(x, use_running_average=not train)
                bn += 1
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)  # flatten the final grid (fc6 input)
        x = nn.relu(nn.Dense(cfg.fc_features, dtype=cfg.dtype)(x))
        x = nn.relu(nn.Dense(cfg.fc_features, dtype=cfg.dtype)(x))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32)(x)


def vgg_param_shardings(params, mesh: Mesh):
    """Same conv-model FSDP rule set as ResNet/Inception."""
    from tensorflowonspark_tpu.models.resnet import resnet_param_shardings

    return resnet_param_shardings(params, mesh)


def loss_fn(model: VGG):
    """``loss(params, batch_stats, batch) -> (loss, new_batch_stats)`` —
    the shared BN-classifier loss (same contract as ResNet's)."""
    from tensorflowonspark_tpu.models.resnet import loss_fn as _bn_loss

    return _bn_loss(model)
