"""Inception-v3 (the reference's headline image-classification model).

Parity note: the reference's scaling story was Inception-v3 training on
the Yahoo grid (upstream README "near-linear scalability" chart; example
trees ``examples/imagenet/inception`` and ``examples/slim`` — SURVEY.md
§2.4, §6). This is a from-scratch flax implementation of the v3
architecture (Szegedy et al. 2015, "Rethinking the Inception
Architecture"), not a port of the reference's TF-slim code.

TPU-first design notes:

- NHWC, convs in bf16 on the MXU, BatchNorm statistics in fp32 — same
  dtype recipe as :mod:`tensorflowonspark_tpu.models.resnet`.
- SAME padding everywhere (the original mixes VALID/SAME; uniform SAME
  keeps every grid size a clean power-of-two fraction of the input and
  avoids odd XLA padding configs — at 299x299 the A/B/C grids come out
  38/19/10 instead of the classic 35/17/8, within a few % of the same
  FLOPs).
- The factorized 7x1/1x7 and 3x1/1x3 convs of the B/C blocks are kept:
  they are exactly the shapes XLA tiles well (long-thin convs lower to
  efficient MXU matmuls after im2col).
- Block counts and branch widths are config, so a ``tiny()`` variant
  exercises every block type in CI without the 23M-param footprint.
- ``inception_param_shardings``: FSDP over output channels, BN params
  replicated — ZeRO-style DP, same rules as ResNet.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflowonspark_tpu.ops.batch_norm import FusedBatchNorm


@dataclasses.dataclass(frozen=True)
class InceptionConfig:
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16
    # Classic v3: 3 A-blocks (35-grid), 4 B-blocks (17-grid), 2 C-blocks
    # (8-grid), separated by the two reduction blocks.
    num_a_blocks: int = 3
    num_b_blocks: int = 4
    num_c_blocks: int = 2
    width_mult: float = 1.0  # scales every branch width (tiny/CI configs)
    aux_logits: bool = True  # 17-grid auxiliary classifier (train only)
    aux_weight: float = 0.4  # paper's aux-loss discount
    dropout_rate: float = 0.0  # pre-classifier dropout (needs a dropout rng)

    @staticmethod
    def v3(**overrides) -> "InceptionConfig":
        return InceptionConfig(**overrides)

    @staticmethod
    def tiny(**overrides) -> "InceptionConfig":
        """One of each block type at 1/8 width: every code path, tiny cost."""
        base = dict(
            num_classes=10,
            num_a_blocks=1,
            num_b_blocks=1,
            num_c_blocks=1,
            width_mult=0.125,
            aux_logits=False,
        )
        base.update(overrides)
        return InceptionConfig(**base)

    def w(self, channels: int) -> int:
        """Scale a classic branch width, keeping lanes-friendly multiples."""
        return max(8, int(channels * self.width_mult) // 8 * 8)


class _ConvBN(nn.Module):
    """conv -> BN(fp32 stats) -> relu, the unit every Inception branch uses."""

    features: int
    kernel: tuple[int, int]
    dtype: jnp.dtype
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(
            self.features,
            self.kernel,
            self.strides,
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
        )(x)
        # Fused-statistics BN: one variadic-reduce pass per direction for
        # the channel stats (fp32 accumulation over bf16 streams) — see
        # the chip-profile rationale in ops/batch_norm.py and the
        # measurement history in models/resnet.py.
        # name= pins the pre-round-3 auto-name (nn.BatchNorm era) so
        # checkpoints saved before the FusedBatchNorm swap restore as-is.
        x = FusedBatchNorm(
            momentum=0.9,
            epsilon=1e-3,
            dtype=self.dtype,
            name="BatchNorm_0",
        )(x, use_running_average=not train)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    """35-grid block: 1x1 / 5x5 / double-3x3 / pool branches."""

    cfg: InceptionConfig
    pool_features: int

    @nn.compact
    def __call__(self, x, train: bool):
        cfg, dt = self.cfg, self.cfg.dtype
        b1 = _ConvBN(cfg.w(64), (1, 1), dt)(x, train)
        b5 = _ConvBN(cfg.w(48), (1, 1), dt)(x, train)
        b5 = _ConvBN(cfg.w(64), (5, 5), dt)(b5, train)
        b3 = _ConvBN(cfg.w(64), (1, 1), dt)(x, train)
        b3 = _ConvBN(cfg.w(96), (3, 3), dt)(b3, train)
        b3 = _ConvBN(cfg.w(96), (3, 3), dt)(b3, train)
        bp = _ConvBN(self.pool_features, (1, 1), dt)(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class ReductionA(nn.Module):
    """35 -> 17 grid: stride-2 3x3 / stride-2 double-3x3 / maxpool."""

    cfg: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg, dt = self.cfg, self.cfg.dtype
        b3 = _ConvBN(cfg.w(384), (3, 3), dt, strides=(2, 2))(x, train)
        bd = _ConvBN(cfg.w(64), (1, 1), dt)(x, train)
        bd = _ConvBN(cfg.w(96), (3, 3), dt)(bd, train)
        bd = _ConvBN(cfg.w(96), (3, 3), dt, strides=(2, 2))(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionB(nn.Module):
    """17-grid block with factorized 7x1/1x7 convs."""

    cfg: InceptionConfig
    c7: int  # width of the factorized-conv channel (classic: 128..192)

    @nn.compact
    def __call__(self, x, train: bool):
        cfg, dt = self.cfg, self.cfg.dtype
        c7 = cfg.w(self.c7)
        out = cfg.w(192)
        b1 = _ConvBN(out, (1, 1), dt)(x, train)
        b7 = _ConvBN(c7, (1, 1), dt)(x, train)
        b7 = _ConvBN(c7, (1, 7), dt)(b7, train)
        b7 = _ConvBN(out, (7, 1), dt)(b7, train)
        bd = _ConvBN(c7, (1, 1), dt)(x, train)
        bd = _ConvBN(c7, (7, 1), dt)(bd, train)
        bd = _ConvBN(c7, (1, 7), dt)(bd, train)
        bd = _ConvBN(c7, (7, 1), dt)(bd, train)
        bd = _ConvBN(out, (1, 7), dt)(bd, train)
        bp = _ConvBN(out, (1, 1), dt)(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class ReductionB(nn.Module):
    """17 -> 8 grid."""

    cfg: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg, dt = self.cfg, self.cfg.dtype
        b3 = _ConvBN(cfg.w(192), (1, 1), dt)(x, train)
        b3 = _ConvBN(cfg.w(320), (3, 3), dt, strides=(2, 2))(b3, train)
        b7 = _ConvBN(cfg.w(192), (1, 1), dt)(x, train)
        b7 = _ConvBN(cfg.w(192), (1, 7), dt)(b7, train)
        b7 = _ConvBN(cfg.w(192), (7, 1), dt)(b7, train)
        b7 = _ConvBN(cfg.w(192), (3, 3), dt, strides=(2, 2))(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionC(nn.Module):
    """8-grid block: the widest one (1x3/3x1 split branches)."""

    cfg: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg, dt = self.cfg, self.cfg.dtype
        b1 = _ConvBN(cfg.w(320), (1, 1), dt)(x, train)
        b3 = _ConvBN(cfg.w(384), (1, 1), dt)(x, train)
        b3 = jnp.concatenate(
            [
                _ConvBN(cfg.w(384), (1, 3), dt)(b3, train),
                _ConvBN(cfg.w(384), (3, 1), dt)(b3, train),
            ],
            axis=-1,
        )
        bd = _ConvBN(cfg.w(448), (1, 1), dt)(x, train)
        bd = _ConvBN(cfg.w(384), (3, 3), dt)(bd, train)
        bd = jnp.concatenate(
            [
                _ConvBN(cfg.w(384), (1, 3), dt)(bd, train),
                _ConvBN(cfg.w(384), (3, 1), dt)(bd, train),
            ],
            axis=-1,
        )
        bp = _ConvBN(cfg.w(192), (1, 1), dt)(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class _AuxHead(nn.Module):
    """17-grid auxiliary classifier (training regularizer, paper §4)."""

    cfg: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg, dt = self.cfg, self.cfg.dtype
        x = nn.avg_pool(x, (5, 5), strides=(3, 3), padding="VALID")
        x = _ConvBN(cfg.w(128), (1, 1), dt)(x, train)
        x = _ConvBN(cfg.w(768), (5, 5), dt)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32)(x)


class InceptionV3(nn.Module):
    """Returns fp32 logits; ``(logits, aux_logits)`` when the aux head runs
    (``aux_logits`` configs under ``train=True``)."""

    config: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        dt = cfg.dtype
        x = x.astype(dt)
        # Stem: 299 -> /8 grid, 192 channels.
        x = _ConvBN(cfg.w(32), (3, 3), dt, strides=(2, 2))(x, train)
        x = _ConvBN(cfg.w(32), (3, 3), dt)(x, train)
        x = _ConvBN(cfg.w(64), (3, 3), dt)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = _ConvBN(cfg.w(80), (1, 1), dt)(x, train)
        x = _ConvBN(cfg.w(192), (3, 3), dt)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        # A tower (pool branch widens 32 -> 64 like the classic stack).
        for i in range(cfg.num_a_blocks):
            x = InceptionA(cfg, cfg.w(32 if i == 0 else 64))(x, train)
        x = ReductionA(cfg)(x, train)
        # B tower: factorized-conv width ramps 128 -> 160 -> 192.
        for i in range(cfg.num_b_blocks):
            frac = i / max(cfg.num_b_blocks - 1, 1)
            x = InceptionB(cfg, c7=int(128 + 64 * frac))(x, train)
        aux = None
        if cfg.aux_logits and train:
            aux = _AuxHead(cfg, name="aux")(x, train)
        x = ReductionB(cfg)(x, train)
        for _ in range(cfg.num_c_blocks):
            x = InceptionC(cfg)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        if cfg.dropout_rate > 0:
            x = nn.Dropout(cfg.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)
        return (logits, aux) if aux is not None else logits


def inception_param_shardings(params, mesh: Mesh):
    """FSDP rules: the conv-model rule set is shared with ResNet (shard
    conv output channels / FC rows over 'fsdp', replicate BN params)."""
    from tensorflowonspark_tpu.models.resnet import resnet_param_shardings

    return resnet_param_shardings(params, mesh)


def loss_fn(model: InceptionV3, dropout_rng: jax.Array | None = None):
    """Build ``loss(params, batch_stats, batch) -> (loss, new_batch_stats)``
    for batches ``{'image', 'label'}``; folds the aux head in at
    ``cfg.aux_weight`` when it runs."""
    import optax

    cfg = model.config

    def loss(params, batch_stats, batch):
        rngs = (
            {"dropout": dropout_rng}
            if cfg.dropout_rate > 0 and dropout_rng is not None
            else None
        )
        out, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
            rngs=rngs,
        )
        logits, aux = out if isinstance(out, tuple) else (out, None)
        ce = optax.softmax_cross_entropy_with_integer_labels
        total = ce(logits, batch["label"]).mean()
        if aux is not None:
            total = total + cfg.aux_weight * ce(aux, batch["label"]).mean()
        return total, mutated["batch_stats"]

    return loss
