"""Model-zoo factory: name -> ready-to-train model bundle.

Parity note: the reference's ``examples/slim`` tree exposed TF-slim's
``nets_factory.get_network_fn(name)`` so scripts could pick any zoo
model by flag (SURVEY.md §2.4 "v1-era legacy"). This is that surface for
the rebuild's families: pass ``--model resnet50`` (etc.) in a driver
script and train without writing model code.

Every entry resolves to a :class:`ZooEntry` carrying the flax module, an
example input maker (for ``model.init``), the mesh sharding rules, and a
loss builder with the right signature family:

- image classifiers (``kind='image'``): batches ``{'image','label'}``,
  loss ``(params, batch_stats, batch) -> (loss, new_batch_stats)``
- token models (``kind='tokens'``): batches ``{'tokens'}`` (Llama) or
  model-specific (BERT — see its example), loss from the model module
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np


@dataclasses.dataclass(frozen=True)
class ZooEntry:
    name: str
    kind: str  # 'image' | 'tokens' | 'segmentation'
    model: Any  # flax module
    make_input: Callable[[int], dict]  # batch_size -> example numpy batch
    param_shardings: Callable  # (params, mesh) -> sharding tree
    make_loss: Callable[[], Callable]  # () -> loss fn for the kind
    has_batch_stats: bool = False


def _image_entry(name, model, shardings, loss_builder, size, classes):
    def make_input(b):
        rng = np.random.default_rng(0)
        return {
            "image": rng.random((b, size, size, 3)).astype(np.float32),
            "label": rng.integers(0, classes, size=b).astype(np.int32),
        }

    return ZooEntry(
        name=name,
        kind="image",
        model=model,
        make_input=make_input,
        param_shardings=shardings,
        make_loss=lambda: loss_builder(model),
        has_batch_stats=True,
    )


def _build_resnet(variant, tiny, num_classes):
    from tensorflowonspark_tpu.models import resnet

    cfg = (
        resnet.ResNetConfig.tiny(num_classes=num_classes)
        if tiny
        else getattr(resnet.ResNetConfig, variant)(num_classes=num_classes)
    )
    return _image_entry(
        variant,
        resnet.ResNet(cfg),
        resnet.resnet_param_shardings,
        resnet.loss_fn,
        32 if tiny else 224,
        num_classes,
    )


def _build_inception(tiny, num_classes):
    from tensorflowonspark_tpu.models import inception

    cfg = (
        inception.InceptionConfig.tiny(num_classes=num_classes)
        if tiny
        else inception.InceptionConfig.v3(num_classes=num_classes)
    )
    return _image_entry(
        "inception_v3",
        inception.InceptionV3(cfg),
        inception.inception_param_shardings,
        inception.loss_fn,
        64 if tiny else 299,
        num_classes,
    )


def _build_vgg(variant, tiny, num_classes):
    from tensorflowonspark_tpu.models import vgg

    cfg = (
        vgg.VGGConfig.tiny(num_classes=num_classes)
        if tiny
        else getattr(vgg.VGGConfig, variant)(num_classes=num_classes)
    )
    return _image_entry(
        variant,
        vgg.VGG(cfg),
        vgg.vgg_param_shardings,
        vgg.loss_fn,
        32 if tiny else 224,
        num_classes,
    )


def _build_vit(tiny, num_classes):
    from tensorflowonspark_tpu.models import vit

    cfg = (
        vit.ViTConfig.tiny(num_classes=num_classes)
        if tiny
        else vit.ViTConfig.b16(num_classes=num_classes)
    )
    entry = _image_entry(
        "vit_b16",
        vit.ViT(cfg),
        vit.vit_param_shardings,
        vit.loss_fn,
        cfg.image_size,
        num_classes,
    )
    # ViT has no BatchNorm; its loss passes the (empty) stats through
    return dataclasses.replace(entry, has_batch_stats=False)


def _build_unet(tiny, num_classes):
    from tensorflowonspark_tpu.models import unet

    cfg = (
        unet.UNetConfig.tiny()
        if tiny
        else unet.UNetConfig(num_classes=num_classes)
    )
    model = unet.UNet(cfg)

    def make_input(b):
        rng = np.random.default_rng(0)
        s = 16 if tiny else 128
        return {
            "image": rng.random((b, s, s, 3)).astype(np.float32),
            "mask": rng.integers(0, cfg.num_classes, size=(b, s, s)).astype(
                np.int32
            ),
        }

    return ZooEntry(
        name="unet",
        kind="segmentation",
        model=model,
        make_input=make_input,
        param_shardings=unet.unet_param_shardings,
        make_loss=lambda: unet.loss_fn(model),
    )


def _build_bert(tiny):
    from tensorflowonspark_tpu.models import bert

    cfg = bert.BertConfig.tiny() if tiny else bert.BertConfig()
    model = bert.BertForMLM(cfg)

    def make_input(b):
        rng = np.random.default_rng(0)
        s = min(cfg.max_seq_len, 32 if tiny else 128)
        return {
            "tokens": rng.integers(0, cfg.vocab_size, size=(b, s)).astype(
                np.int32
            ),
            "targets": rng.integers(0, cfg.vocab_size, size=(b, s)).astype(
                np.int32
            ),
        }

    def make_loss():
        import optax

        def loss(params, batch):
            logits = model.apply({"params": params}, batch["tokens"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["targets"]
            ).mean()

        return loss

    return ZooEntry(
        name="bert_base",
        kind="tokens",
        model=model,
        make_input=make_input,
        param_shardings=bert.bert_param_shardings,
        make_loss=make_loss,
    )


def _build_llama(variant, tiny):
    from tensorflowonspark_tpu.models import llama as L

    if tiny:
        cfg = L.LlamaConfig.tiny(
            sliding_window=8 if variant == "mistral_7b" else None,
            attention_bias=variant == "qwen2_7b",
        )
    elif variant == "llama2_7b":
        cfg = L.LlamaConfig.llama2_7b()
    elif variant == "llama3_8b":
        cfg = L.LlamaConfig.llama3_8b()
    elif variant == "mistral_7b":
        cfg = L.LlamaConfig.mistral_7b()
    elif variant == "qwen2_7b":
        cfg = L.LlamaConfig.qwen2_7b()
    else:  # llama_1b (the BASELINE.md benchmark config)
        cfg = L.LlamaConfig.llama_1b()
    model = L.Llama(cfg)

    def make_input(b):
        rng = np.random.default_rng(0)
        s = min(cfg.max_seq_len, 32 if tiny else 1024)
        return {
            "tokens": rng.integers(0, cfg.vocab_size, size=(b, s + 1)).astype(
                np.int32
            )
        }

    def make_loss():
        token_loss = L.llama_loss_fn(model)
        return lambda p, batch: token_loss(p, batch["tokens"])

    return ZooEntry(
        name=variant,
        kind="tokens",
        model=model,
        make_input=make_input,
        param_shardings=L.llama_param_shardings,
        make_loss=make_loss,
    )


_BUILDERS: dict[str, Callable[..., ZooEntry]] = {
    "resnet18": lambda tiny, nc: _build_resnet("resnet18", tiny, nc),
    "resnet34": lambda tiny, nc: _build_resnet("resnet34", tiny, nc),
    "resnet50": lambda tiny, nc: _build_resnet("resnet50", tiny, nc),
    "resnet101": lambda tiny, nc: _build_resnet("resnet101", tiny, nc),
    "inception_v3": lambda tiny, nc: _build_inception(tiny, nc),
    "vgg11": lambda tiny, nc: _build_vgg("vgg11", tiny, nc),
    "vgg16": lambda tiny, nc: _build_vgg("vgg16", tiny, nc),
    "vit_b16": lambda tiny, nc: _build_vit(tiny, nc),
    "unet": lambda tiny, nc: _build_unet(tiny, nc),
    "bert_base": lambda tiny, nc: _build_bert(tiny),
    "llama_1b": lambda tiny, nc: _build_llama("llama_1b", tiny),
    "llama2_7b": lambda tiny, nc: _build_llama("llama2_7b", tiny),
    "llama3_8b": lambda tiny, nc: _build_llama("llama3_8b", tiny),
    "mistral_7b": lambda tiny, nc: _build_llama("mistral_7b", tiny),
    "qwen2_7b": lambda tiny, nc: _build_llama("qwen2_7b", tiny),
}


def names() -> list[str]:
    return sorted(_BUILDERS)


def build(name: str, tiny: bool = False, num_classes: int = 1000) -> ZooEntry:
    """Resolve a zoo model by name (the ``nets_factory`` surface).

    ``tiny=True`` swaps in each family's CI-size config; ``num_classes``
    applies to the image families.
    """
    if name not in _BUILDERS:
        raise KeyError(
            f"unknown zoo model {name!r}; available: {', '.join(names())}"
        )
    return _BUILDERS[name](tiny, num_classes)
