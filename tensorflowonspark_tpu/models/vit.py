"""Vision Transformer (ViT-B/16 family) image classifier.

Parity note: the reference's image families were conv nets
(inception/cifar10/slim — SURVEY.md §2.4); ViT extends the zoo with the
transformer-era image model. From-scratch flax, not a port.

TPU-first design notes:

- Patchify as a strided conv (one MXU matmul per patch grid), tokens
  thereafter — everything downstream is the same batched-matmul shape
  the MXU likes. Encoder blocks are pre-LN with GELU MLPs, bf16 compute
  and fp32 LayerNorm statistics.
- Attention runs through ``ops.attention.dot_product_attention``
  (non-causal full attention; the flash kernel and mesh paths apply at
  long token counts, XLA einsum at ViT's 197-token scale).
- NO BatchNorm: ViT's LayerNorm has no cross-batch statistics, so the
  bandwidth-bound stats passes that cap the conv nets (see
  ops/batch_norm.py) structurally don't exist here; the model is
  matmul-dominated — the shape TPUs are best at.
- ``vit_param_shardings``: 2D kernels shard over ('fsdp', 'model') like
  the Llama rules; LN/bias/cls/pos replicated.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflowonspark_tpu.compute import layout

from tensorflowonspark_tpu.ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"

    @staticmethod
    def b16(**overrides) -> "ViTConfig":
        return ViTConfig(**overrides)

    @staticmethod
    def tiny(**overrides) -> "ViTConfig":
        base = dict(
            image_size=16,
            patch_size=4,
            hidden_size=32,
            num_layers=2,
            num_heads=4,
            num_classes=10,
            dtype=jnp.float32,
        )
        base.update(overrides)
        return ViTConfig(**base)


class _Block(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = cfg.hidden_size
        head_dim = h // cfg.num_heads
        b, n, _ = x.shape

        y = nn.LayerNorm(dtype=cfg.dtype)(x)
        q = nn.Dense(h, dtype=cfg.dtype, name="q_proj")(y)
        k = nn.Dense(h, dtype=cfg.dtype, name="k_proj")(y)
        v = nn.Dense(h, dtype=cfg.dtype, name="v_proj")(y)
        q = q.reshape(b, n, cfg.num_heads, head_dim)
        k = k.reshape(b, n, cfg.num_heads, head_dim)
        v = v.reshape(b, n, cfg.num_heads, head_dim)
        a = dot_product_attention(
            q, k, v, causal=False, impl=cfg.attention_impl
        )
        a = a.reshape(b, n, h)
        x = x + nn.Dense(h, dtype=cfg.dtype, name="o_proj")(a)

        y = nn.LayerNorm(dtype=cfg.dtype)(x)
        y = nn.Dense(h * cfg.mlp_ratio, dtype=cfg.dtype, name="up")(y)
        y = nn.gelu(y)
        y = nn.Dense(h, dtype=cfg.dtype, name="down")(y)
        return x + y


class ViT(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        if cfg.image_size % cfg.patch_size:
            raise ValueError(
                f"image_size {cfg.image_size} not divisible by "
                f"patch_size {cfg.patch_size}"
            )
        x = x.astype(cfg.dtype)
        p = cfg.patch_size
        x = nn.Conv(
            cfg.hidden_size,
            (p, p),
            strides=(p, p),
            padding="VALID",
            dtype=cfg.dtype,
            name="patch_embed",
        )(x)
        b = x.shape[0]
        x = x.reshape(b, -1, cfg.hidden_size)
        n = x.shape[1]
        cls = self.param(
            "cls",
            nn.initializers.zeros,
            (1, 1, cfg.hidden_size),
            jnp.float32,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, cfg.hidden_size)).astype(cfg.dtype), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, n + 1, cfg.hidden_size),
            jnp.float32,
        )
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = _Block(cfg, name=f"layer{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype)(x)
        # Classifier head in fp32 for a stable softmax, from the CLS token.
        return nn.Dense(cfg.num_classes, dtype=jnp.float32)(x[:, 0])


def vit_param_shardings(params, mesh: Mesh):
    """2D kernels over ('fsdp','model'); everything else replicated.

    Like the conv nets' rules, a dim that does not divide its mesh axis
    falls back to replication for that dim (e.g. the (hidden, 10)
    classifier head under model>1) rather than erroring at device_put.
    """
    return layout.param_shardings(params, mesh, "vit")


def loss_fn(model: ViT):
    """Stats-less image loss ``(params, batch) -> scalar`` (ViT has no
    BatchNorm; zoo consumers branch on ``has_batch_stats`` for the
    signature family, like the token models)."""
    import optax

    def loss(params, batch):
        logits = model.apply(
            {"params": params}, batch["image"], train=True
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()

    return loss
