"""Model zoo.

The reference's "models" were its examples (SURVEY.md §2.4: MNIST keras/
estimator, U-Net segmentation, cifar10/inception legacy); the rebuild's
baseline configs add ResNet-50, BERT-base, and Llama-2 (BASELINE.md). All
models are flax.linen modules designed for bf16 MXU math and mesh sharding
(see each model's ``param_shardings``).
"""

from tensorflowonspark_tpu.models import mnist  # noqa: F401
from tensorflowonspark_tpu.models.bert import (  # noqa: F401
    Bert,
    BertConfig,
    BertForClassification,
    BertForMLM,
    bert_param_shardings,
)
from tensorflowonspark_tpu.models.inception import (  # noqa: F401
    InceptionConfig,
    InceptionV3,
    inception_param_shardings,
)
from tensorflowonspark_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    Llama,
    llama_param_shardings,
)
from tensorflowonspark_tpu.models.speculative import (  # noqa: F401
    speculative_accept,
    speculative_generate,
)
from tensorflowonspark_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNetConfig,
    resnet_param_shardings,
)
from tensorflowonspark_tpu.models.unet import (  # noqa: F401
    UNet,
    UNetConfig,
    unet_param_shardings,
)
from tensorflowonspark_tpu.models.vgg import (  # noqa: F401
    VGG,
    VGGConfig,
    vgg_param_shardings,
)
from tensorflowonspark_tpu.models import zoo  # noqa: F401
