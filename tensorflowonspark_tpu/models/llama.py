"""Llama-family decoder (the flagship model for the FSDP baseline).

TPU-first design notes:

- bf16 activations/params with fp32 RMSNorm accumulations and fp32 softmax
  (inside the attention op) — the MXU-friendly mix.
- RoPE applied functionally; no Python control flow under jit.
- Grouped-query attention via the shared
  :func:`tensorflowonspark_tpu.ops.attention.dot_product_attention`
  (Pallas flash kernel on TPU, XLA fallback elsewhere).
- Megatron-style mesh sharding rules in :func:`llama_param_shardings`:
  'fsdp' shards every matrix's non-TP dimension; 'model' (TP) shards
  attention heads and MLP hidden. DP/FSDP is the parity target
  (BASELINE.md Llama-2-7B config); TP rules ship so scaling past FSDP is a
  sharding change, not a rewrite (SURVEY.md §2.3 implication).
"""

from __future__ import annotations

import dataclasses
import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from tensorflowonspark_tpu.compute import layout

from tensorflowonspark_tpu.ops.attention import dot_product_attention
from tensorflowonspark_tpu.ops.lora import (
    LoraTensor,
    MultiLoraTensor,
    lora_apply,
    multi_lora_apply,
)
from tensorflowonspark_tpu.ops.quant import QuantTensor, quantized_dot


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3-style RoPE frequency rescaling (hashable, so configs
    carrying it still key jit/lru caches).

    ``kind='llama3'``: wavelengths longer than
    ``original_max_seq_len/low_freq_factor`` divide by ``factor``,
    shorter than ``original_max_seq_len/high_freq_factor`` stay, the
    band between interpolates smoothly — the published Llama-3.1
    long-context recipe. ``kind='linear'``: every frequency divides by
    ``factor`` (position interpolation).
    """

    kind: str = "llama3"
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_seq_len: int = 8192


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rope_scaling: RopeScaling | None = None
    rms_norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    remat: bool = True
    # 'full': recompute the whole block in backward (min memory);
    # 'dots': save matmul/einsum outputs, recompute the cheap elementwise
    # ops only (XLA's dots_with_no_batch_dims_saveable — usually the best
    # MFU/memory point when the model fits); ignored when remat=False.
    remat_policy: str = "full"
    # MoE: when num_experts > 0 every block's MLP is a routed expert bank
    # (expert-parallel over the mesh 'expert' axis — parallel/moe.py).
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    # Qwen2-family QKV bias: the q/k/v projections carry bias vectors
    # (o_proj and the MLP stay bias-free, matching the architecture).
    attention_bias: bool = False
    # Sliding-window (Mistral-style local) attention: each query
    # attends only the last `sliding_window` positions. None = full
    # causal attention. Applies to training/prefill (xla + flash — the
    # flash kernel restricts its grids to the window span — and the SP
    # impls: ring shortens its rotation to the owners in reach, ulysses
    # passes the window to each device's local attention) AND cached
    # decode (position-plane-masked reads of the full-length cache).
    sliding_window: int | None = None
    # Rolling KV cache: cache only this many slots (>= sliding_window
    # + write width - 1) instead of max_seq_len, with slot = position %
    # kv_cache_len. Requires sliding_window (full attention needs every
    # position). THE long-context serving lever for windowed models:
    # Mistral-7B at 32k context holds a 4.3 GB/row dense cache vs ~0.5
    # GB rolling at window 4096. None = dense (max_seq_len slots).
    kv_cache_len: int | None = None
    # KV-cache storage: "model" (= dtype, exact) or "int8" (per-token
    # per-head max-abs quantization — halves the cache HBM footprint
    # AND the per-step cache read traffic that bounds long-context
    # decode; dequant folds into the attention einsums, so no bf16 copy
    # of the cache ever exists). Decode-side only; training is
    # unaffected (no cache).
    kv_cache_dtype: str = "model"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama_1b(**overrides) -> "LlamaConfig":
        """The BASELINE.md single-chip benchmark config (953M params)."""
        base = dict(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_layers=16,
            num_heads=16,
            num_kv_heads=16,
            max_seq_len=1024,
            dtype=jnp.bfloat16,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def mistral_7b(**overrides) -> "LlamaConfig":
        """Mistral-7B-v0.1: Llama layout + GQA + sliding-window 4096
        (import real weights with ``tools/import_hf_llama`` — the
        converter accepts ``model_type: mistral``)."""
        base = dict(
            vocab_size=32000,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            # matches the checkpoint's max_position_embeddings (the
            # importer produces the same value), NOT the 4096 window —
            # context runs far past the window by design
            max_seq_len=32768,
            rope_theta=10000.0,
            sliding_window=4096,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def llama3_8b(**overrides) -> "LlamaConfig":
        """Llama-3.1-8B: GQA 32/8, 128k vocab, llama3 RoPE scaling
        (the importer maps HF rope_scaling type 'llama3' to the same
        :class:`RopeScaling`)."""
        base = dict(
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            max_seq_len=131072,
            rope_theta=500000.0,
            rope_scaling=RopeScaling(
                kind="llama3",
                factor=8.0,
                low_freq_factor=1.0,
                high_freq_factor=4.0,
                original_max_seq_len=8192,
            ),
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def qwen2_7b(**overrides) -> "LlamaConfig":
        """Qwen2-7B: Llama layout + QKV bias + GQA, 1M rope theta
        (import real weights with ``tools/import_hf_llama`` — the
        converter accepts ``model_type: qwen2``)."""
        base = dict(
            vocab_size=152064,
            hidden_size=3584,
            intermediate_size=18944,
            num_layers=28,
            num_heads=28,
            num_kv_heads=4,
            max_seq_len=32768,
            rope_theta=1_000_000.0,
            rms_norm_eps=1e-6,
            attention_bias=True,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Test-size config (also used by __graft_entry__ dry runs)."""
        base = dict(
            vocab_size=256,
            hidden_size=128,
            intermediate_size=256,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            max_seq_len=128,
        )
        base.update(overrides)
        return LlamaConfig(**base)


class RMSNorm(nn.Module):
    eps: float
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (norm * scale).astype(self.dtype)


def _scaled_rope_freqs(
    d: int, theta: float, scaling: "RopeScaling | None"
) -> jax.Array:
    """Base (or rescaled) inverse frequencies for head dim ``d``."""
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    if scaling is None:
        return freqs
    if scaling.kind == "linear":
        return freqs / scaling.factor
    if scaling.kind != "llama3":
        raise ValueError(f"unknown rope_scaling kind {scaling.kind!r}")
    # Llama-3.1 recipe: long wavelengths compress by `factor`, short
    # ones stay, the band between interpolates (matches the HF
    # implementation — logit-tested in tests/test_hf_import.py)
    orig = float(scaling.original_max_seq_len)
    low_wavelen = orig / scaling.low_freq_factor
    high_wavelen = orig / scaling.high_freq_factor
    wavelen = 2.0 * jnp.pi / freqs
    smooth = (orig / wavelen - scaling.low_freq_factor) / (
        scaling.high_freq_factor - scaling.low_freq_factor
    )
    interp = (1.0 - smooth) * freqs / scaling.factor + smooth * freqs
    out = jnp.where(wavelen > low_wavelen, freqs / scaling.factor, freqs)
    mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
    return jnp.where(mid, interp, out)


def rope(
    x: jax.Array,
    positions: jax.Array,
    theta: float,
    scaling: "RopeScaling | None" = None,
) -> jax.Array:
    """Rotary embedding; x (B, S, H, D), positions (B, S)."""
    d = x.shape[-1]
    freqs = _scaled_rope_freqs(d, theta, scaling)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, S, D/2)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class QDense(nn.Module):
    """Dense (bias-free by default) that also accepts int8
    ``QuantTensor`` kernels.

    With a regular array kernel this is exactly ``nn.Dense(use_bias=
    False, dtype=...)``; with a quantized kernel (``ops/quant.py``,
    e.g. a tree from ``quantize_tree``) the dot runs against the int8
    weight with the per-channel scales folded into the fp32 accumulator
    — weights stay int8 in HBM through the whole decode, which is the
    point (decode is weight-bandwidth-bound). A ``LoraTensor`` kernel
    (``ops/lora.py:add_lora``) runs base + low-rank adapter with the
    base stop-gradiented — the parameter-efficient fine-tune path.
    ``use_bias=True`` adds a bias vector AFTER whichever kernel path
    ran (Qwen2-family QKV projections; the bias is tiny and composes
    with quant/LoRA kernels untouched)."""

    features: int
    dtype: jnp.dtype
    use_bias: bool = False

    @nn.compact
    def __call__(self, x, adapter_ids=None):
        kernel = self.param(
            "kernel",
            nn.initializers.normal(0.02),
            (jnp.shape(x)[-1], self.features),
        )
        x = x.astype(self.dtype)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,)
            )
            apply = lambda y: y + bias.astype(y.dtype)  # noqa: E731
        else:
            apply = lambda y: y  # noqa: E731
        if isinstance(kernel, QuantTensor):
            return apply(quantized_dot(x, kernel))
        if isinstance(kernel, LoraTensor):
            return apply(lora_apply(x, kernel))
        if isinstance(kernel, MultiLoraTensor):
            # Per-row adapter routing (the multi-tenant serving path);
            # ids default to slot 0, the bank's zero adapter == base.
            if adapter_ids is None:
                adapter_ids = jnp.zeros((jnp.shape(x)[0],), jnp.int32)
            return apply(multi_lora_apply(x, kernel, adapter_ids))
        return apply(x @ kernel.astype(self.dtype))


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, x, positions, segment_ids=None, decode=False, padded=False,
        adapter_ids=None,
    ):
        cfg = self.cfg
        dense = lambda feats, name, b=False: QDense(  # noqa: E731
            feats, cfg.dtype, use_bias=b, name=name
        )
        ab = cfg.attention_bias
        q = dense(cfg.num_heads * cfg.head_dim, "q_proj", ab)(x, adapter_ids)
        k = dense(cfg.num_kv_heads * cfg.head_dim, "k_proj", ab)(
            x, adapter_ids
        )
        v = dense(cfg.num_kv_heads * cfg.head_dim, "v_proj", ab)(
            x, adapter_ids
        )
        b, s, _ = x.shape
        q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
        q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        if decode:
            if segment_ids is not None and padded:
                raise ValueError(
                    "segment_ids with padded=True is unsupported: padded "
                    "decode writes each row's cache at its own positions "
                    "(mixed-length unpadded prompts), which conflicts "
                    "with packed rows' global slot indexing"
                )
            out = self._cached_attention(q, k, v, positions, padded,
                                         segment_ids)
        else:
            out = dot_product_attention(
                q, k, v, causal=True, segment_ids=segment_ids,
                impl=cfg.attention_impl, window=cfg.sliding_window,
            )
        out = out.reshape(b, s, cfg.num_heads * cfg.head_dim)
        return dense(cfg.hidden_size, "o_proj")(out, adapter_ids)

    def _cached_attention(
        self, q, k, v, positions, padded=False, segment_ids=None
    ):
        """Autoregressive attention against a static-shape KV cache.

        The cache spans ``max_seq_len``. With uniform rows (``padded=
        False``) new K/V land at the scalar running write index
        (``lax.dynamic_update_slice``, so one jit covers prefill and
        every decode step); with ``padded=True`` each row writes at ITS
        OWN positions (a per-row scatter — the right-padded mixed-length
        prompt case, where row r's next slot is its true length). Either
        way the cache slot of a token is its ROW index (== its RoPE
        position for unpacked rows), so the slot-index query mask below
        excludes both unwritten slots and the right-padding garbage a
        padded prefill writes past each row's true length (those slots
        are only ever attended after being overwritten by that row's
        real decode tokens).

        Packed rows (``segment_ids`` given): each slot also records its
        token's segment id in the cache, and queries additionally mask
        by id EQUALITY — cross-document reads are structurally
        impossible, which is what makes packed prefill/scoring sound.
        RoPE ``positions`` restart per document and therefore DIVERGE
        from slot indices; the slot mask uses the running write index,
        never ``positions``. Ids must be unique per document within a
        row (``packed_loss_mask`` canonicalizes). Unpacked callers
        store zeros everywhere, making the id-equality term vacuous —
        one code path, one compiled program.

        Decode is HBM-bandwidth-bound; plain einsum is the right shape
        for it (flash targets the O(S^2) training pass).
        """
        cfg = self.cfg
        b, s = q.shape[:2]
        C = cfg.kv_cache_len or cfg.max_seq_len
        rolling = C < cfg.max_seq_len
        if rolling:
            if cfg.sliding_window is None:
                raise ValueError(
                    f"kv_cache_len={C} < max_seq_len needs sliding_window "
                    "(full attention reads every position)"
                )
            if segment_ids is not None:
                # Packed rows restart positions per document, so
                # position % C COLLIDES across documents (doc2's slot 0
                # overwrites doc1's) — silently wrong, so refuse.
                raise ValueError(
                    "segment_ids (packed rows) are unsupported with a "
                    "rolling kv_cache_len: per-document positions "
                    "collide under slot = position % C"
                )
            if C < cfg.sliding_window + s - 1:
                # a write of s positions may not wrap onto slots that
                # queries in the SAME call still attend
                raise ValueError(
                    f"kv_cache_len={C} must be >= sliding_window "
                    f"({cfg.sliding_window}) + write width ({s}) - 1; "
                    "prefill in smaller chunks (the engine's "
                    "prefill_chunk) or grow the cache"
                )
        int8_kv = cfg.kv_cache_dtype == "int8"
        kv_store = jnp.int8 if int8_kv else cfg.dtype
        ck = self.variable(
            "cache", "k", jnp.zeros,
            (b, C, cfg.num_kv_heads, cfg.head_dim), kv_store,
        )
        cv = self.variable(
            "cache", "v", jnp.zeros,
            (b, C, cfg.num_kv_heads, cfg.head_dim), kv_store,
        )
        if int8_kv:
            # Per-token per-head max-abs scales. fp32: 4 bytes per
            # head-token next to head_dim int8 bytes (~3% at d=128).
            cks = self.variable(
                "cache", "k_scale", jnp.zeros,
                (b, C, cfg.num_kv_heads), jnp.float32,
            )
            cvs = self.variable(
                "cache", "v_scale", jnp.zeros,
                (b, C, cfg.num_kv_heads), jnp.float32,
            )
        cs = self.variable(
            "cache", "seg", jnp.zeros, (b, C), jnp.int32
        )
        if cfg.sliding_window is not None:
            # Each slot's RoPE position: the window masks by POSITION
            # distance, not slot distance — for packed rows continuing
            # an earlier document, the two diverge (other documents'
            # tokens occupy the slots between). Rolling caches init to
            # -1: slot 0's "position 0" would otherwise be
            # indistinguishable from never-written for early queries.
            # NOTE for cache consumers that build fresh rows outside
            # flax (the serving engine): this is the ONE cache leaf
            # whose init is non-zero under rolling — see init_cache().
            cp = self.variable(
                "cache", "pos",
                lambda: jnp.full((b, C), -1 if rolling else 0, jnp.int32),
            )
        ci = self.variable(
            "cache", "idx", lambda: jnp.zeros((), jnp.int32)
        )
        cur = ci.value
        seg = (
            jnp.zeros((b, s), jnp.int32)
            if segment_ids is None
            else segment_ids.astype(jnp.int32)
        )

        def store(x):
            """What lands in the cache for new K/V rows: the model-dtype
            values, or (int8, scale) with symmetric max-abs rounding."""
            if not int8_kv:
                return x.astype(cfg.dtype), None
            xf = x.astype(jnp.float32)
            scale = jnp.maximum(
                jnp.max(jnp.abs(xf), axis=-1), 1e-8
            ) * (1.0 / 127.0)
            q8 = jnp.clip(
                jnp.round(xf / scale[..., None]), -127, 127
            ).astype(jnp.int8)
            return q8, scale

        k_new, ks_new = store(k)
        v_new, vs_new = store(v)
        if rolling:
            # slot = position % C for BOTH padded and uniform rows: the
            # mask below is purely positional (via the pos plane), so
            # the write-index bookkeeping of the dense branches is
            # unnecessary here
            rows = jnp.arange(b)[:, None]
            slots = positions % C
            ck.value = ck.value.at[rows, slots].set(k_new)
            cv.value = cv.value.at[rows, slots].set(v_new)
            if int8_kv:
                cks.value = cks.value.at[rows, slots].set(ks_new)
                cvs.value = cvs.value.at[rows, slots].set(vs_new)
            cs.value = cs.value.at[rows, slots].set(seg)
            cp.value = cp.value.at[rows, slots].set(positions)
            slot_q = None  # unused: rolling masks by position only
        elif padded:
            rows = jnp.arange(b)[:, None]
            ck.value = ck.value.at[rows, positions].set(k_new)
            cv.value = cv.value.at[rows, positions].set(v_new)
            if int8_kv:
                cks.value = cks.value.at[rows, positions].set(ks_new)
                cvs.value = cvs.value.at[rows, positions].set(vs_new)
            if cfg.sliding_window is not None:
                cp.value = cp.value.at[rows, positions].set(positions)
            # positions ARE the slots here (unpacked rows only; the
            # packed+padded combination is rejected in __call__)
            slot_q = positions
        else:
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k_new, (0, cur, 0, 0)
            )
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v_new, (0, cur, 0, 0)
            )
            if int8_kv:
                cks.value = jax.lax.dynamic_update_slice(
                    cks.value, ks_new, (0, cur, 0)
                )
                cvs.value = jax.lax.dynamic_update_slice(
                    cvs.value, vs_new, (0, cur, 0)
                )
            cs.value = jax.lax.dynamic_update_slice(cs.value, seg, (0, cur))
            if cfg.sliding_window is not None:
                cp.value = jax.lax.dynamic_update_slice(
                    cp.value, positions.astype(jnp.int32), (0, cur)
                )
            slot_q = jnp.broadcast_to(
                (cur + jnp.arange(s, dtype=jnp.int32))[None, :], (b, s)
            )
        ci.value = cur + s
        # Grouped einsum against the un-repeated cache: materializing a
        # jnp.repeat of (b, max_seq_len, heads, d) K/V — plus an fp32 copy
        # — per layer per step would multiply exactly the HBM traffic that
        # bounds decode. Only the (b, h, q, k) logits live in fp32.
        #
        # int8 path: the HBM stream stays int8 (the astype below fuses
        # into the einsum as an operand producer); the K scale factors
        # OUT of the head_dim contraction and multiplies the fp32
        # logits per key slot, and the V scale folds into the fp32
        # probs before they narrow — dequantized K/V never exist as
        # arrays.
        rep = cfg.num_heads // cfg.num_kv_heads
        qg = q.reshape(b, s, cfg.num_kv_heads, rep, cfg.head_dim)
        logits = (
            jnp.einsum(
                "bqhrd,bkhd->bhrqk",
                qg,
                ck.value.astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            )
            * cfg.head_dim**-0.5
        )
        if int8_kv:
            # (b, S, h) -> (b, h, 1, 1, S) against logits (b, h, r, q, S)
            logits = logits * cks.value.transpose(0, 2, 1)[:, :, None, None, :]
        if rolling:
            # Purely positional masking: a slot is attended iff its
            # recorded position is real (>= 0; stale slots were
            # overwritten, and their OLD positions are <= q - C <= q - W
            # so the window term also kills any that survived), causal
            # (<= q), and within the window (> q - W).
            kplane = cp.value[:, None, None, None, :]
            qcol = positions[:, None, None, :, None]
            mask = (
                (kplane >= 0)
                & (kplane <= qcol)
                & (kplane > qcol - cfg.sliding_window)
            )
            mask = mask & (
                cs.value[:, None, None, None, :]
                == seg[:, None, None, :, None]
            )
        else:
            key_pos = jnp.arange(C)
            mask = (
                key_pos[None, None, None, None, :]
                <= slot_q[:, None, None, :, None]
            )
            mask = mask & (
                cs.value[:, None, None, None, :]
                == seg[:, None, None, :, None]
            )
            if cfg.sliding_window is not None:
                # sliding window by RoPE-position distance (slots
                # already bounded above by slot_q): attend only the
                # last W positions
                mask = mask & (
                    cp.value[:, None, None, None, :]
                    > positions[:, None, None, :, None]
                    - cfg.sliding_window
                )
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        if int8_kv:
            probs = probs * cvs.value.transpose(0, 2, 1)[:, :, None, None, :]
        probs = probs.astype(cfg.dtype)
        out = jnp.einsum("bhrqk,bkhd->bqhrd", probs, cv.value.astype(cfg.dtype))
        return out.reshape(b, s, cfg.num_heads, cfg.head_dim)


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, adapter_ids=None):
        cfg = self.cfg
        dense = lambda feats, name: QDense(  # noqa: E731
            feats, cfg.dtype, name=name
        )
        gate = dense(cfg.intermediate_size, "gate_proj")(x, adapter_ids)
        up = dense(cfg.intermediate_size, "up_proj")(x, adapter_ids)
        return dense(cfg.hidden_size, "down_proj")(
            nn.silu(gate) * up, adapter_ids
        )


class Block(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, x, positions, segment_ids=None, decode=False, padded=False,
        adapter_ids=None,
    ):
        cfg = self.cfg
        h = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="attn_norm")(x),
            positions,
            segment_ids,
            decode,
            padded,
            adapter_ids,
        )
        if cfg.num_experts > 0:
            from tensorflowonspark_tpu.parallel.moe import MoEConfig, MoEMLP

            mlp = MoEMLP(
                MoEConfig(
                    num_experts=cfg.num_experts,
                    top_k=cfg.moe_top_k,
                    capacity_factor=cfg.moe_capacity_factor,
                    hidden_size=cfg.hidden_size,
                    intermediate_size=cfg.intermediate_size,
                    dtype=cfg.dtype,
                ),
                name="mlp",
            )
        else:
            mlp = MLP(cfg, name="mlp")
        normed = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="mlp_norm")(h)
        if cfg.num_experts > 0:
            return h + mlp(normed)  # MoE routes by token, not adapter
        return h + mlp(normed, adapter_ids)


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self,
        tokens,
        positions=None,
        segment_ids=None,
        decode=False,
        return_hidden=False,
        padded=False,
        adapter_ids=None,
    ):
        """tokens (B, S) int32 -> logits (B, S, vocab).

        ``decode=True`` runs against per-layer KV caches (apply with
        ``mutable=["cache"]``; see :func:`generate`): ``positions`` must
        then be the absolute positions of ``tokens`` in the sequence.
        ``padded=True`` (decode only) makes each row write the cache at
        its own positions — the right-padded mixed-length prompt case
        (:func:`generate` with ``prompt_lengths``).

        ``segment_ids`` (B, S) marks packed documents: attention is
        masked by id EQUALITY and RoPE positions restart at adjacency
        boundaries, so ids must be unique per document within a row
        (:func:`llama_loss_fn` canonicalizes adjacency runs for you).
        Works with ``decode=True`` too — the KV cache records each
        slot's segment id and masks reads by it, so packed prefill and
        scoring (and continuing a chosen document by passing its id
        with the new tokens' positions) never attend across documents.
        Only the ``padded=True`` combination is rejected: per-row
        scatter slots conflict with packed rows' global slot indexing.

        ``adapter_ids`` (B,) int32 routes each row through its slot of
        any ``MultiLoraTensor`` adapter banks in the params
        (``ops/lora.py:multi_lora_bank`` — multi-tenant serving); None
        routes every row to slot 0, the bank's exact-base zero adapter.
        Ignored when the params hold no banks.

        ``return_hidden=True`` returns ``(hidden, lm_head)`` instead of
        logits — the final-norm hidden states (B, S, H) and the untied
        head weight — so callers can run the vocab projection in chunks
        (:func:`llama_loss_fn` with ``logit_chunk``) without ever
        materializing the (B, S, vocab) fp32 logits.
        """
        cfg = self.cfg
        if positions is None:
            idx = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape
            )
            if segment_ids is None:
                positions = idx
            else:
                # Packed sequences: RoPE positions restart at each
                # document boundary. A position's document start is the
                # running max of boundary indices up to it.
                new_doc = jnp.concatenate(
                    [
                        jnp.ones_like(segment_ids[:, :1], dtype=bool),
                        segment_ids[:, 1:] != segment_ids[:, :-1],
                    ],
                    axis=1,
                )
                doc_start = jax.lax.cummax(
                    jnp.where(new_doc, idx, 0), axis=1
                )
                positions = idx - doc_start
        embed = self.param(
            "embed",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size),
        )
        if isinstance(embed, QuantTensor):
            # gather int8 rows, then scale: the table stays int8 in HBM.
            # Per-row (axis=0) scales — quantize_tree's default for the
            # embedding — gather alongside the rows; axis=-1 broadcasts.
            rows = embed.q[tokens].astype(jnp.float32)
            scale = embed.scale[tokens] if embed.axis == 0 else embed.scale
            x = (rows * scale).astype(cfg.dtype)
        else:
            x = embed[tokens].astype(cfg.dtype)
        if cfg.remat and not decode:
            # Rematerialize each layer's activations in backward: trades
            # FLOPs for HBM, the standard long-sequence TPU memory lever.
            # (decode stays out of the remat'd arg list: as a traced
            # operand it could not drive Python control flow.)
            if cfg.remat_policy not in ("full", "dots", "none"):
                raise ValueError(
                    f"unknown remat_policy {cfg.remat_policy!r}; "
                    "expected 'full', 'dots', or 'none'"
                )
            policy = (
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                if cfg.remat_policy == "dots"
                else None
            )
            block = nn.remat(Block, static_argnums=(), policy=policy)
            for i in range(cfg.num_layers):
                # decode/padded stay at their (static) defaults — passing
                # them positionally through remat would trace them
                x = block(cfg, name=f"layer{i}")(
                    x, positions, segment_ids, adapter_ids=adapter_ids
                )
        else:
            for i in range(cfg.num_layers):
                x = Block(cfg, name=f"layer{i}")(
                    x, positions, segment_ids, decode, padded, adapter_ids
                )
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="final_norm")(x)
        # untied output head
        head = self.param(
            "lm_head",
            nn.initializers.normal(0.02),
            (cfg.hidden_size, cfg.vocab_size),
        )
        if return_hidden:
            return x, head
        if isinstance(head, QuantTensor):
            return quantized_dot(x, head).astype(jnp.float32)
        return (x @ head.astype(cfg.dtype)).astype(jnp.float32)


def llama_param_shardings(params, mesh: Mesh):
    """Mesh sharding rules for a Llama param tree — the declarative
    'llama' table in :mod:`tensorflowonspark_tpu.compute.layout`.

    Megatron layout on the ('fsdp', 'model') axes; biases/norms replicated.
    With mesh model=1 this degrades to pure FSDP (the Llama-2-7B baseline
    config); with fsdp=1 to pure TP. MoE expert banks and LoRA factor
    halves are rules in the same table, so model-level and module-level
    specs cannot diverge.
    """
    return layout.param_shardings(params, mesh, "llama")


def init_cache(shapes):
    """Fresh cache values for a tree of ShapeDtypeStructs (the serving
    engine builds per-row caches from ``jax.eval_shape`` rather than a
    real ``model.init`` — an init-valued apply would also WRITE its
    dummy token into the cache). This is the single source of truth for
    cache-leaf init values outside flax: everything zero-fills EXCEPT
    the position plane, which is -1 ("never written") so a rolling
    cache cannot mistake a stale slot for a valid position 0. Keep in
    lockstep with the ``self.variable`` inits in ``_cached_attention``.
    """

    def init(path, s):
        name = str(getattr(path[-1], "key", path[-1]))
        if name == "pos":
            return jnp.full(s.shape, -1, s.dtype)
        return jnp.zeros(s.shape, s.dtype)

    return jax.tree_util.tree_map_with_path(init, shapes)


def decode_cache_spec(x: jax.Array) -> PartitionSpec:
    """PartitionSpec for one KV-cache leaf under mesh-sharded decode:
    K/V (B, S, kv_heads, D) shard batch on 'data' and heads on 'model'
    (each TP shard holds only its heads' cache — the HBM split that
    makes 7B-class serving fit), int8-KV scale planes (B, S, kv_heads)
    follow their heads, the segment-id plane (B, S) shards on 'data',
    the scalar write index replicates. Declared as
    ``layout.DECODE_CACHE_SPECS``."""
    return layout.decode_cache_spec(x)


def generate(
    model: "Llama",
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    min_p: float | None = None,
    rng: jax.Array | None = None,
    eos_id: int | None = None,
    prompt_lengths: jax.Array | None = None,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Autoregressive sampling with a KV cache: (B, S) -> (B, max_new_tokens).

    One jitted prefill over the prompt, then single-token steps against
    the per-layer caches — static shapes throughout, so the whole loop is
    one compilation (cached across calls with the same model and shapes).
    ``temperature=0`` is greedy argmax; otherwise tokens are sampled from
    ``logits / temperature``, optionally truncated to the ``top_k`` most
    likely tokens and/or the smallest nucleus with cumulative probability
    ``top_p`` (top-k applies first, like the standard decoding stacks)
    and/or ``min_p`` (keep tokens whose probability is at least
    ``min_p`` times the most likely token's; composes with k/p by mask
    intersection).

    Mixed-length prompts: RIGHT-pad ``prompt`` and pass
    ``prompt_lengths`` (B,) true lengths. Each row samples its first
    token from the logits at ITS last real position, decodes from its
    own position, and overwrites its padding slots in the KV cache as it
    goes (per-row scatter writes; the positional mask keeps not-yet-
    overwritten padding invisible). Without ``prompt_lengths`` the
    prompt must be unpadded (all rows the same true length).

    ``eos_id``: rows that emit it are finished — their remaining slots
    fill with ``eos_id`` — and decoding exits EARLY once every row has
    finished (a ``lax.while_loop`` instead of the fixed-length scan; the
    output stays statically (B, max_new_tokens)). Decode is weight-read
    bound, so stopping at the true lengths is a proportional wall-clock
    win on typical generation workloads.

    ``mesh``: run the whole decode sharded over a device mesh — weights
    TP-sharded on the ``model`` axis (:func:`llama_param_shardings`,
    the Megatron layout; XLA inserts the per-layer psums over ICI),
    batch and KV caches sharded on ``data``/``model``
    (:func:`decode_cache_spec`). This is the multi-chip serving path:
    7B-class weights exceed one chip's HBM, so TP over ≥2 chips is the
    capacity floor, and DP over 'data' scales throughput. Tokens are
    bit-identical to the single-device decode up to TP reduction
    order. Requires batch % mesh 'data' extent == 0 and num_kv_heads %
    'model' extent == 0.
    """
    cfg = model.cfg
    b, s = prompt.shape
    if s + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({cfg.max_seq_len}); the KV cache cannot hold it"
        )
    if top_k is not None and top_k < 1:
        raise ValueError("top_k must be >= 1")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError("top_p must be in (0, 1]")
    if min_p is not None and not (0.0 <= min_p <= 1.0):
        raise ValueError("min_p must be in [0, 1]")
    if temperature == 0.0 and (
        top_k is not None or top_p is not None or min_p is not None
    ):
        raise ValueError(
            "top_k/top_p/min_p require temperature > 0 (temperature=0 is "
            "greedy argmax, which would silently ignore them)"
        )
    rng = jax.random.PRNGKey(0) if rng is None else rng
    if mesh is not None:
        dp = mesh.shape["data"]
        tp = mesh.shape["model"]
        if b % dp:
            raise ValueError(
                f"batch {b} not divisible by the mesh 'data' extent {dp}"
            )
        if cfg.num_kv_heads % tp or cfg.num_heads % tp:
            raise ValueError(
                f"heads ({cfg.num_heads}/{cfg.num_kv_heads} kv) not "
                f"divisible by the mesh 'model' extent {tp}"
            )
        # Commit inputs to their decode shardings; jit then compiles the
        # SPMD program against the committed placements (device_put is a
        # no-op for already-placed serving calls).
        params = jax.device_put(params, llama_param_shardings(params, mesh))
        prompt = jax.device_put(
            prompt, layout.activation_sharding(mesh, "prompt")
        )
        rng = jax.device_put(rng, layout.replicated(mesh))
    # int8 weight-only decode: quantized trees (ops/quant.py
    # quantize_tree) pass straight through — QDense / the embed gather /
    # the head projection consume QuantTensor leaves natively, so the
    # weights stay int8 in HBM for the whole decode.
    run = _build_generate(
        model,
        b,
        s,
        max_new_tokens,
        float(temperature),
        None if top_k is None else int(top_k),
        None if top_p is None else float(top_p),
        None if eos_id is None else int(eos_id),
        padded=prompt_lengths is not None,
        mesh=mesh,
        min_p=None if min_p is None else float(min_p),
    )
    if prompt_lengths is None:
        return run(params, prompt, rng)
    lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if lengths.shape != (b,):
        raise ValueError(
            f"prompt_lengths must have shape ({b},), got {lengths.shape}"
        )
    # host-side range check: out-of-range lengths would clamp/wrap under
    # jit and decode plausible-looking garbage instead of raising
    import numpy as _np

    host = _np.asarray(lengths)
    if (host < 1).any() or (host > s).any():
        raise ValueError(
            f"prompt_lengths must be in [1, {s}] (the padded prompt "
            f"width); got {host.tolist()}"
        )
    if mesh is not None:
        lengths = jax.device_put(
            lengths, layout.activation_sharding(mesh, "per_row")
        )
    return run(params, prompt, rng, lengths)


def sample_logits(
    logits, key, temperature, top_k=None, top_p=None, min_p=None
):
    """Sample next tokens from (B, vocab) logits.

    ``temperature == 0`` is greedy argmax (``key`` unused). Otherwise
    sample from ``logits / temperature``, optionally truncated to the
    ``top_k`` most likely tokens and/or the smallest nucleus with
    cumulative probability ``top_p`` (top-k applies first, matching the
    standard decoding stacks), and/or ``min_p`` (keep tokens whose
    probability is at least ``min_p`` times the most likely token's —
    an elementwise row-max compare on the scaled distribution,
    composing with k/p by mask intersection). Sampling params are
    trace-time constants — callers bake them into their jitted program.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    vocab = logits.shape[-1]
    k_active = top_k is not None and top_k < vocab
    p_active = top_p is not None and top_p < 1.0
    if k_active:
        # lax.top_k beats a full-vocab sort inside the scanned
        # single-token decode loop; when top_p is also set, the
        # nucleus scan then runs on k values instead of the vocab
        sorted_desc = jax.lax.top_k(logits, top_k)[0]
        logits = jnp.where(
            logits < sorted_desc[..., -1, None], -jnp.inf, logits
        )
    elif p_active:
        sorted_desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    if p_active:
        cum = jnp.cumsum(jax.nn.softmax(sorted_desc, axis=-1), axis=-1)
        # index of the last kept token: everything before the point
        # where cumulative mass reaches top_p, and always >= 0 (the
        # most likely token survives even when it alone exceeds p;
        # an index == k clamps to the last top-k entry = keep all)
        cutoff_index = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff_logit = jnp.take_along_axis(
            sorted_desc, cutoff_index, axis=-1
        )
        logits = jnp.where(logits < cutoff_logit, -jnp.inf, logits)
    if min_p is not None and min_p > 0.0:
        # log-space: prob >= min_p * prob_max  <=>  logit >= max + log(m),
        # on the temperature-scaled distribution. The row max survives
        # any k/p mask above (the most likely token is never truncated),
        # and already-masked entries stay -inf, so this intersects.
        floor = jnp.max(logits, axis=-1, keepdims=True) + jnp.log(
            jnp.float32(min_p)
        )
        logits = jnp.where(logits < floor, -jnp.inf, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


@functools.lru_cache(maxsize=32)
def _build_generate(
    model: "Llama",
    b: int,
    s: int,
    max_new_tokens: int,
    temperature: float,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
    padded: bool = False,
    mesh: Mesh | None = None,
    min_p: float | None = None,
):
    """Compile-once generate body per (model config, shapes, sampling
    params).

    flax Modules hash by their dataclass fields, so two ``Llama`` instances
    with equal configs share the cache entry (``Mesh`` hashes by device
    assignment + axis names, so a mesh keys its own entry); a per-call
    ``jax.jit`` would recompile the prefill + scan graph on every
    invocation.
    """

    def constrain_cache(cache):
        # Pin the per-layer KV caches to their decode shardings at the
        # loop boundary; the scan/while carry then keeps them there
        # instead of letting sharding propagation pick (e.g.) a
        # replicated layout whose per-step all-gathers would swamp the
        # HBM-bound decode.
        if mesh is None:
            return cache
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, layout.decode_cache_sharding(mesh, x)
            ),
            cache,
        )

    def sample(logits, key):
        return sample_logits(
            logits, key, temperature, top_k, top_p, min_p
        )

    @jax.jit
    def run(params, prompt, rng, lengths=None):
        positions = jnp.broadcast_to(
            jnp.arange(s, dtype=jnp.int32), (b, s)
        )
        logits, prefill = model.apply(
            {"params": params},
            prompt,
            positions=positions,
            decode=True,
            padded=padded,
            mutable=["cache"],
        )
        keys = jax.random.split(rng, max_new_tokens)
        if padded:
            # each row's first token samples from the logits at ITS
            # last real position; decode continues from its own length
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1
            )[:, 0]
            tok = sample(last, keys[0])
            pos0 = lengths
        else:
            tok = sample(logits[:, -1], keys[0])
            pos0 = jnp.full((b,), s, jnp.int32)

        def decode_step(cache, tok, pos, key):
            logits, updated = model.apply(
                {"params": params, "cache": cache},
                tok[:, None],
                positions=pos[:, None],
                decode=True,
                padded=padded,
                mutable=["cache"],
            )
            return constrain_cache(updated["cache"]), sample(
                logits[:, -1], key
            )

        if eos_id is None:

            def step(carry, key):
                cache, tok, pos = carry
                cache, next_tok = decode_step(cache, tok, pos, key)
                return (cache, next_tok, pos + 1), tok

            init = (constrain_cache(prefill["cache"]), tok, pos0)
            (_, last, _), toks = jax.lax.scan(step, init, keys[1:])
            # scan emitted each step's *input* token; the final sample
            # closes the sequence
            return jnp.concatenate(
                [jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1
            )

        # EOS path: while_loop exits as soon as EVERY row has emitted
        # eos_id; finished rows keep emitting eos_id. Output shape stays
        # statically (B, max_new_tokens).
        buf = jnp.full((b, max_new_tokens), eos_id, jnp.int32)
        buf = buf.at[:, 0].set(tok)
        done = tok == eos_id

        def cond(carry):
            _, _, _, done, _, i = carry
            return (i < max_new_tokens) & ~jnp.all(done)

        def body(carry):
            cache, tok, pos, done, buf, i = carry
            cache, next_tok = decode_step(
                cache, tok, pos, jax.lax.dynamic_index_in_dim(
                    keys, i, keepdims=False
                )
            )
            next_tok = jnp.where(done, eos_id, next_tok)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, next_tok[:, None], i, axis=1
            )
            return (
                cache,
                next_tok,
                pos + 1,
                done | (next_tok == eos_id),
                buf,
                i + 1,
            )

        init = (
            constrain_cache(prefill["cache"]), tok, pos0, done, buf,
            jnp.int32(1),
        )
        (_, _, _, _, buf, _) = jax.lax.while_loop(cond, body, init)
        return buf

    return run


def packed_loss_mask(segment_ids: jax.Array):
    """Loss mask + canonicalized ids for packed rows.

    ``segment_ids`` is (B, S+1), aligned with the (B, S+1) token rows
    ``llama_loss_fn`` trains on. Returns ``(mask, canonical_ids)``:

    - ``mask`` (B, S) float32 — 1 where the target position trains.
      Segment id 0 marks PADDING (the t5x/maxtext convention;
      ``data/packing.py`` emits it): pad targets never train. Positions
      whose NEXT token belongs to a different document are dropped — a
      document's last token must not be trained to predict the next
      document's first.
    - ``canonical_ids`` (B, S+1) — adjacency runs renumbered into
      per-row document indices: attention masks by id EQUALITY, so a
      packer that reuses an id for a later document (e.g.
      [0,0,1,1,0,0]) would silently leak attention between the two
      id-0 documents.

    ``mask.sum()`` is the batch's valid-token count — the exact weight
    to hand ``build_train_step(batch_weight_fn=...)`` when gradient-
    accumulating packed batches (see :func:`packed_valid_count`).
    """
    not_pad = (segment_ids[:, :-1] != 0).astype(jnp.float32)
    new_doc = segment_ids[:, 1:] != segment_ids[:, :-1]
    canonical = jnp.concatenate(
        [
            jnp.zeros_like(segment_ids[:, :1]),
            jnp.cumsum(new_doc.astype(jnp.int32), axis=1),
        ],
        axis=1,
    )
    mask = (canonical[:, :-1] == canonical[:, 1:]).astype(jnp.float32) * not_pad
    return mask, canonical


def packed_valid_count(segment_ids: jax.Array) -> jax.Array:
    """Scalar count of loss-contributing positions in a packed batch —
    ``build_train_step``'s ``batch_weight_fn`` for exact token-weighted
    gradient accumulation over packed/masked CE."""
    mask, _ = packed_loss_mask(segment_ids)
    return jnp.sum(mask)


def llama_loss_fn(model: "Llama", logit_chunk: int | None = None):
    """Next-token loss closure ``(params, tokens(B,S+1)) -> scalar`` that
    also collects sown auxiliary losses (the MoE router load-balancing
    loss — ``parallel/moe.py:MoEMLP``). A bare ``model.apply`` without
    ``mutable=['losses']`` silently discards those, so MoE configs MUST
    train through this (or an equivalent mutable-collecting) loss.

    ``logit_chunk``: compute the vocab projection + cross entropy per
    sequence chunk of this length under ``jax.checkpoint``, so the
    (B, S, vocab) fp32 logits are never materialized (backward
    recomputes each chunk's logits). At seq 4096 / vocab 32000 / b 8 the
    full logits alone are 4.2 GB of HBM — this trades one extra head
    matmul pass for that footprint. Must divide the sequence length.

    Packed sequences: pass ``segment_ids`` (B, S+1), aligned with
    ``tokens`` (``data/packing.py`` produces both). Attention is masked
    within documents (every impl incl. ring/Ulysses SP), positions whose
    NEXT token belongs to a different document are dropped from the loss
    — a document's last token must not be trained to predict the next
    document's first — and segment id 0 marks padding (the t5x/maxtext
    convention): padding positions never contribute loss.
    """

    def loss(params, tokens, segment_ids=None):
        mask = None
        if segment_ids is not None:
            mask, segment_ids = packed_loss_mask(segment_ids)
        seg_in = None if segment_ids is None else segment_ids[:, :-1]
        if logit_chunk is None:
            logits, state = model.apply(
                {"params": params},
                tokens[:, :-1],
                segment_ids=seg_in,
                mutable=["losses"],
            )
            total = cross_entropy_loss(logits, tokens[:, 1:], mask)
        else:
            (hidden, head), state = model.apply(
                {"params": params},
                tokens[:, :-1],
                segment_ids=seg_in,
                return_hidden=True,
                mutable=["losses"],
            )
            b, s, h = hidden.shape
            if s % logit_chunk:
                raise ValueError(
                    f"logit_chunk {logit_chunk} must divide seq len {s}"
                )
            targets = tokens[:, 1:]
            head16 = head.astype(hidden.dtype)
            mc = jnp.ones((b, s), jnp.float32) if mask is None else mask

            @jax.checkpoint
            def chunk_nll_sum(hc, tc, mk):
                # (B, C, H) @ (H, V) -> fp32 logits for this chunk only
                logits = (hc @ head16).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(logp, tc[..., None], axis=-1)
                return jnp.sum(nll[..., 0] * mk)

            n_chunks = s // logit_chunk
            hs = hidden.reshape(b, n_chunks, logit_chunk, h).swapaxes(0, 1)
            ts = targets.reshape(b, n_chunks, logit_chunk).swapaxes(0, 1)
            ms = mc.reshape(b, n_chunks, logit_chunk).swapaxes(0, 1)

            def body(acc, htm):
                hc, tc, mk = htm
                return acc + chunk_nll_sum(hc, tc, mk), None

            total, _ = jax.lax.scan(
                body, jnp.zeros((), jnp.float32), (hs, ts, ms)
            )
            total = total / jnp.maximum(jnp.sum(mc), 1)
        for leaf in jax.tree.leaves(state.get("losses", {})):
            total = total + jnp.sum(leaf)
        return total

    return loss


def cross_entropy_loss(logits: jax.Array, targets: jax.Array, mask=None):
    """Mean next-token cross entropy; logits (B,S,V), targets (B,S)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
    return jnp.mean(nll)
