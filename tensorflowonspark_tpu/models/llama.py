"""Llama-family decoder (the flagship model for the FSDP baseline).

TPU-first design notes:

- bf16 activations/params with fp32 RMSNorm accumulations and fp32 softmax
  (inside the attention op) — the MXU-friendly mix.
- RoPE applied functionally; no Python control flow under jit.
- Grouped-query attention via the shared
  :func:`tensorflowonspark_tpu.ops.attention.dot_product_attention`
  (Pallas flash kernel on TPU, XLA fallback elsewhere).
- Megatron-style mesh sharding rules in :func:`llama_param_shardings`:
  'fsdp' shards every matrix's non-TP dimension; 'model' (TP) shards
  attention heads and MLP hidden. DP/FSDP is the parity target
  (BASELINE.md Llama-2-7B config); TP rules ship so scaling past FSDP is a
  sharding change, not a rewrite (SURVEY.md §2.3 implication).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflowonspark_tpu.ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    remat: bool = True
    # MoE: when num_experts > 0 every block's MLP is a routed expert bank
    # (expert-parallel over the mesh 'expert' axis — parallel/moe.py).
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Test-size config (also used by __graft_entry__ dry runs)."""
        base = dict(
            vocab_size=256,
            hidden_size=128,
            intermediate_size=256,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            max_seq_len=128,
        )
        base.update(overrides)
        return LlamaConfig(**base)


class RMSNorm(nn.Module):
    eps: float
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (norm * scale).astype(self.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x (B, S, H, D), positions (B, S)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, S, D/2)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=cfg.dtype, name=name,
            kernel_init=nn.initializers.normal(0.02),
        )
        q = dense(cfg.num_heads * cfg.head_dim, "q_proj")(x)
        k = dense(cfg.num_kv_heads * cfg.head_dim, "k_proj")(x)
        v = dense(cfg.num_kv_heads * cfg.head_dim, "v_proj")(x)
        b, s, _ = x.shape
        q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        out = dot_product_attention(
            q, k, v, causal=True, segment_ids=segment_ids,
            impl=cfg.attention_impl,
        )
        out = out.reshape(b, s, cfg.num_heads * cfg.head_dim)
        return dense(cfg.hidden_size, "o_proj")(out)


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, dtype=cfg.dtype, name=name,
            kernel_init=nn.initializers.normal(0.02),
        )
        gate = dense(cfg.intermediate_size, "gate_proj")(x)
        up = dense(cfg.intermediate_size, "up_proj")(x)
        return dense(cfg.hidden_size, "down_proj")(nn.silu(gate) * up)


class Block(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        h = x + Attention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="attn_norm")(x),
            positions,
            segment_ids,
        )
        if cfg.num_experts > 0:
            from tensorflowonspark_tpu.parallel.moe import MoEConfig, MoEMLP

            mlp = MoEMLP(
                MoEConfig(
                    num_experts=cfg.num_experts,
                    top_k=cfg.moe_top_k,
                    capacity_factor=cfg.moe_capacity_factor,
                    hidden_size=cfg.hidden_size,
                    intermediate_size=cfg.intermediate_size,
                    dtype=cfg.dtype,
                ),
                name="mlp",
            )
        else:
            mlp = MLP(cfg, name="mlp")
        return h + mlp(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="mlp_norm")(h)
        )


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, positions=None, segment_ids=None):
        """tokens (B, S) int32 -> logits (B, S, vocab)."""
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape
            )
        embed = self.param(
            "embed",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size),
        )
        x = embed[tokens].astype(cfg.dtype)
        block = Block
        if cfg.remat:
            # Rematerialize each layer's activations in backward: trades
            # FLOPs for HBM, the standard long-sequence TPU memory lever.
            block = nn.remat(Block, static_argnums=())
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer{i}")(x, positions, segment_ids)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="final_norm")(x)
        # untied output head
        head = self.param(
            "lm_head",
            nn.initializers.normal(0.02),
            (cfg.hidden_size, cfg.vocab_size),
        )
        return (x @ head.astype(cfg.dtype)).astype(jnp.float32)


def llama_param_shardings(params, mesh: Mesh):
    """Mesh sharding rules for a Llama param tree.

    Megatron layout on the ('fsdp', 'model') axes; biases/norms replicated.
    With mesh model=1 this degrades to pure FSDP (the Llama-2-7B baseline
    config); with fsdp=1 to pure TP.
    """

    def rule(path, leaf) -> NamedSharding:
        names = [
            p.key for p in path if isinstance(p, jax.tree_util.DictKey)
        ]
        joined = "/".join(names)
        ndim = leaf.ndim
        if ndim <= 1:
            return NamedSharding(mesh, P())
        if ndim == 3:  # MoE expert banks (E, d, f) / (E, f, d)
            from tensorflowonspark_tpu.parallel.moe import (
                moe_expert_bank_spec,
            )

            return NamedSharding(mesh, moe_expert_bank_spec(joined))
        if "router" in joined:
            return NamedSharding(mesh, P())
        if "embed" in joined:
            return NamedSharding(mesh, P("fsdp", "model"))
        if "lm_head" in joined:
            return NamedSharding(mesh, P("fsdp", "model"))
        if any(k in joined for k in ("q_proj", "k_proj", "v_proj")):
            return NamedSharding(mesh, P("fsdp", "model"))  # col-parallel
        if "o_proj" in joined:
            return NamedSharding(mesh, P("model", "fsdp"))  # row-parallel
        if any(k in joined for k in ("gate_proj", "up_proj")):
            return NamedSharding(mesh, P("fsdp", "model"))
        if "down_proj" in joined:
            return NamedSharding(mesh, P("model", "fsdp"))
        return NamedSharding(mesh, P("fsdp"))

    return jax.tree_util.tree_map_with_path(rule, params)


def llama_loss_fn(model: "Llama"):
    """Next-token loss closure ``(params, tokens(B,S+1)) -> scalar`` that
    also collects sown auxiliary losses (the MoE router load-balancing
    loss — ``parallel/moe.py:MoEMLP``). A bare ``model.apply`` without
    ``mutable=['losses']`` silently discards those, so MoE configs MUST
    train through this (or an equivalent mutable-collecting) loss."""

    def loss(params, tokens):
        logits, state = model.apply(
            {"params": params}, tokens[:, :-1], mutable=["losses"]
        )
        total = cross_entropy_loss(logits, tokens[:, 1:])
        for leaf in jax.tree.leaves(state.get("losses", {})):
            total = total + jnp.sum(leaf)
        return total

    return loss


def cross_entropy_loss(logits: jax.Array, targets: jax.Array, mask=None):
    """Mean next-token cross entropy; logits (B,S,V), targets (B,S)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
    return jnp.mean(nll)
