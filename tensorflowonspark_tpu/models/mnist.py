"""MNIST models — the canonical first example, as in the reference
(``examples/mnist/keras/mnist_spark.py:main_fun`` built a small Keras
dense net; SURVEY.md §2.4)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax


class MLP(nn.Module):
    """512-512-10 dense net (mirror of the reference example's Keras model)."""

    hidden: int = 512
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1).astype(self.dtype)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        return nn.Dense(10, dtype=self.dtype)(x)


class CNN(nn.Module):
    """Small convnet (conv-pool x2 + dense), bf16-friendly."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        return nn.Dense(10, dtype=self.dtype)(x)


def loss_fn(apply_fn):
    """Build a ``loss(params, batch)`` for batches {'image', 'label'}."""

    def loss(params, batch):
        logits = apply_fn({"params": params}, batch["image"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()

    return loss


def accuracy(apply_fn, params, batch) -> jax.Array:
    logits = apply_fn({"params": params}, batch["image"])
    return jnp.mean(jnp.argmax(logits, -1) == batch["label"])


def synthetic_batch(rng: jax.Array | int, batch_size: int):
    """Deterministic fake MNIST batch (no dataset download in this env)."""
    key = jax.random.PRNGKey(rng) if isinstance(rng, int) else rng
    kimg, klab = jax.random.split(key)
    return {
        "image": jax.random.uniform(kimg, (batch_size, 28, 28, 1)),
        "label": jax.random.randint(klab, (batch_size,), 0, 10),
    }
