"""ResNet-v1.5 family (ResNet-50 is the BASELINE.md image config).

Parity note: the reference's image-classification story was the
Inception/cifar10 example trees and the "near-linear scaling" README chart
(SURVEY.md §2.4, §6); the rebuild's baseline names ResNet-50 as the image
workload. This is a from-scratch flax implementation, not a port.

TPU-first design notes:

- NHWC layout throughout (XLA's native TPU conv layout); convs in bf16 so
  they tile onto the MXU, BatchNorm statistics accumulated in fp32.
- v1.5 variant (stride-2 in the 3x3 of the bottleneck, not the 1x1) — the
  standard throughput/accuracy tradeoff for accelerator training.
- No Python control flow under jit; the block stack is unrolled at trace
  time from a static per-stage spec.
- ``resnet_param_shardings``: batch-stat and scale/bias params replicated;
  large conv kernels and the FC layer sharded over 'fsdp' for ZeRO-style
  data parallelism. TP of convs is not worth it at ResNet scale.
"""

from __future__ import annotations

import dataclasses
import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflowonspark_tpu.compute import layout

from tensorflowonspark_tpu.ops.batch_norm import FusedBatchNorm


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)
    bottleneck: bool = True
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @staticmethod
    def resnet18(**kw) -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(2, 2, 2, 2), bottleneck=False, **kw)

    @staticmethod
    def resnet34(**kw) -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=False, **kw)

    @staticmethod
    def resnet50(**kw) -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=True, **kw)

    @staticmethod
    def resnet101(**kw) -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(3, 4, 23, 3), bottleneck=True, **kw)

    @staticmethod
    def tiny(**overrides) -> "ResNetConfig":
        """Test-size config: 2 stages, thin width, bottleneck on."""
        base = dict(stage_sizes=(1, 1), width=8, num_classes=10)
        base.update(overrides)
        return ResNetConfig(**base)


class _ConvBN(nn.Module):
    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int]
    dtype: jnp.dtype
    act: bool = True

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(
            self.features,
            self.kernel,
            self.strides,
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
        )(x)
        # Fused-statistics BN (ops/batch_norm.py): the round-3 chip profile
        # showed 48% of the ResNet-50 step in separate BN stats reduction
        # passes under nn.BatchNorm + autodiff; the custom-VJP op computes
        # both channel statistics per direction in ONE variadic-reduce
        # pass over the bf16 activations (stats accumulate fp32).
        # name= pins the pre-round-3 auto-name (nn.BatchNorm era) so
        # checkpoints saved before the FusedBatchNorm swap restore as-is.
        x = FusedBatchNorm(
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            name="BatchNorm_0",
        )(x, use_running_average=not train)
        return nn.relu(x) if self.act else x


class BasicBlock(nn.Module):
    features: int
    strides: tuple[int, int]
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool):
        residual = x
        y = _ConvBN(self.features, (3, 3), self.strides, self.dtype)(x, train)
        y = _ConvBN(self.features, (3, 3), (1, 1), self.dtype, act=False)(y, train)
        if residual.shape != y.shape:
            residual = _ConvBN(
                self.features, (1, 1), self.strides, self.dtype, act=False
            )(residual, train)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    features: int
    strides: tuple[int, int]
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool):
        residual = x
        y = _ConvBN(self.features, (1, 1), (1, 1), self.dtype)(x, train)
        # v1.5: the stride lives on the 3x3, not the first 1x1.
        y = _ConvBN(self.features, (3, 3), self.strides, self.dtype)(y, train)
        y = _ConvBN(self.features * 4, (1, 1), (1, 1), self.dtype, act=False)(y, train)
        if residual.shape != y.shape:
            residual = _ConvBN(
                self.features * 4, (1, 1), self.strides, self.dtype, act=False
            )(residual, train)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        block = BottleneckBlock if cfg.bottleneck else BasicBlock
        x = x.astype(cfg.dtype)
        x = _ConvBN(cfg.width, (7, 7), (2, 2), cfg.dtype)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, size in enumerate(cfg.stage_sizes):
            for i in range(size):
                strides = (2, 2) if stage > 0 and i == 0 else (1, 1)
                x = block(cfg.width * 2**stage, strides, cfg.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # Classifier head in fp32 for a stable softmax.
        return nn.Dense(cfg.num_classes, dtype=jnp.float32)(x)


def resnet_param_shardings(params, mesh: Mesh):
    """FSDP rules: shard large kernels' output-channel dim over 'fsdp';
    replicate BN scale/bias (tiny) — the declarative 'resnet' table in
    :mod:`tensorflowonspark_tpu.compute.layout`."""
    return layout.param_shardings(params, mesh, "resnet")


def loss_fn(model: ResNet):
    """Build ``loss(params, batch_stats, batch) -> (loss, new_batch_stats)``
    for batches {'image', 'label'}."""
    import optax

    def loss(params, batch_stats, batch):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
        )
        l = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        return l, mutated["batch_stats"]

    return loss
