"""Speculative decoding — draft-and-verify greedy generation.

A small DRAFT model proposes ``k`` tokens autoregressively; the TARGET
model scores all ``k+1`` positions in ONE forward and keeps the longest
prefix it agrees with plus its own correction token. Greedy speculative
decoding emits EXACTLY the target model's greedy sequence (the
acceptance rule only ever keeps tokens the target itself would have
picked) — tested token-identically against :func:`...llama.generate`.

Why it wins on TPU: single-token decode is HBM-bandwidth-bound — every
step reads every weight once. Verification reads the target weights
once per ``a+1`` emitted tokens (``a`` = accepted drafts), and the
(B, k+1) verify forward is a better MXU shape than k+1 single-token
steps. Net speedup ≈ (accepted+1) / (k·cost_draft/cost_target + 1).

Cache discipline (no rollback needed): both models run their KV caches
through the per-row scatter path (``padded=True``), where a token's
slot IS its position and writes land BEFORE attention in each forward
(``llama.py:_cached_attention``). Rejected drafts leave stale cache
entries only at positions ≥ the next iteration's write window, and
every such slot is overwritten by that window before any query's
position reaches it — so acceptance just moves the position counters.

Reference parity note: the reference had no decode path at all
(SURVEY.md §2.2 — its serving story was per-executor SavedModel
replay); this module is capability beyond the reference, built on the
same KV-cache machinery as :func:`...llama.generate`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflowonspark_tpu.compute import layout

__all__ = ["speculative_generate", "speculative_accept"]


def speculative_accept(key, t_probs, d_probs, drafts):
    """One speculative-SAMPLING verification (Leviathan/Chen rejection
    rule): accept draft ``x_j`` with probability ``min(1, p_j(x_j) /
    q_j(x_j))``; at the first rejection sample from the residual
    ``normalize(max(p_j - q_j, 0))``; if all ``k`` drafts survive,
    sample the bonus token from ``p_k``. The emitted tokens are then
    distributed EXACTLY as if each had been sampled from the target
    distribution ``p`` — for ANY draft distribution ``q`` (the draft
    only moves the acceptance rate). Monte-Carlo-verified in
    ``tests/test_speculative.py``.

    Args: ``t_probs (B, k+1, V)`` target probabilities, ``d_probs
    (B, k, V)`` draft probabilities, ``drafts (B, k)`` the draft's
    samples. Returns ``(emit, accepted)``: ``emit (B, k+1)`` holds the
    accepted drafts in ``[0, accepted)`` and the residual/bonus sample
    at index ``accepted`` (later entries are padding), ``accepted
    (B,)`` in ``[0, k]``.
    """
    b, kp1, v = t_probs.shape
    k = kp1 - 1
    key_u, key_r = jax.random.split(key)
    u = jax.random.uniform(key_u, (b, k), jnp.float32)
    p_x = jnp.take_along_axis(t_probs[:, :k], drafts[..., None], -1)[..., 0]
    q_x = jnp.take_along_axis(d_probs, drafts[..., None], -1)[..., 0]
    # u < p/q  <=>  u*q < p (no divide; q=0 with p>0 accepts, both 0
    # rejects — the residual then resamples safely)
    accept = u * q_x < p_x
    accepted = jnp.sum(
        jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
    )
    # q padded with a zero row at j=k: the all-accepted bonus case then
    # falls out of the same residual formula (residual = p_k - 0 = p_k)
    q_pad = jnp.concatenate(
        [d_probs, jnp.zeros((b, 1, v), d_probs.dtype)], axis=1
    )
    p_at = jnp.take_along_axis(
        t_probs, accepted[:, None, None], axis=1
    )[:, 0]
    q_at = jnp.take_along_axis(q_pad, accepted[:, None, None], axis=1)[:, 0]
    residual = jnp.clip(
        p_at.astype(jnp.float32) - q_at.astype(jnp.float32), 0.0, None
    )
    # p == q exactly -> empty residual, but rejection then has
    # probability zero anyway; guard the log with p itself
    degenerate = jnp.sum(residual, axis=-1, keepdims=True) <= 0
    weights = jnp.where(degenerate, p_at.astype(jnp.float32), residual)
    corr = jax.random.categorical(key_r, jnp.log(weights + 1e-38)).astype(
        jnp.int32
    )
    pad = jnp.concatenate([drafts, drafts[:, -1:]], axis=1)
    j_idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    emit = jnp.where(j_idx == accepted[:, None], corr[:, None], pad)
    return emit, accepted


def speculative_generate(
    model,
    params,
    draft_model,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    k: int = 4,
    eos_id: int | None = None,
    prompt_lengths: jax.Array | None = None,
    mesh: Mesh | None = None,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Speculative decode: (B, S) int32 -> (B, max_new_tokens).

    ``temperature == 0`` (default): token-for-token identical to
    ``generate(model, params, prompt, max_new_tokens, eos_id=...)``
    (greedy) for ANY draft model — the draft only changes speed, never
    output. ``temperature > 0``: speculative SAMPLING — the draft
    samples ``k`` proposals at the same temperature and the target
    accepts/resamples via the rejection rule
    (:func:`speculative_accept`), so emitted tokens are distributed
    exactly as target-only sampling; ``rng`` seeds it. top-k/top-p
    truncation is not offered here (it would change the distribution
    the acceptance rule preserves).

    ``k`` is the number of draft proposals per verification; both
    models need ``max_seq_len >= S + max_new_tokens + k`` (the verify
    window may scratch up to ``k`` slots past the emitted text). Rows
    finish independently on ``eos_id`` and the loop exits early once
    every row is done. Mixed-length prompts: RIGHT-pad and pass
    ``prompt_lengths`` (B,), exactly like ``generate``.

    ``mesh``: the TARGET runs TP/DP-sharded exactly like ``generate``'s
    mesh path (weights on 'model', batch + caches on 'data'); the DRAFT
    is fully replicated with only its batch/cache sharded on 'data' —
    a draft is small by construction, and replication frees it from the
    target's head-divisibility constraints.
    """
    b, s = prompt.shape
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    rng = jax.random.PRNGKey(0) if rng is None else rng
    for name, cfg in (("model", model.cfg), ("draft_model", draft_model.cfg)):
        if s + max_new_tokens + k > cfg.max_seq_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + k "
                f"({k}) exceeds {name}.cfg.max_seq_len ({cfg.max_seq_len})"
            )
    if mesh is not None:
        from tensorflowonspark_tpu.models.llama import llama_param_shardings

        dp = mesh.shape["data"]
        tp = mesh.shape["model"]
        if b % dp:
            raise ValueError(
                f"batch {b} not divisible by the mesh 'data' extent {dp}"
            )
        if model.cfg.num_kv_heads % tp or model.cfg.num_heads % tp:
            raise ValueError(
                f"target heads ({model.cfg.num_heads}/"
                f"{model.cfg.num_kv_heads} kv) not divisible by the mesh "
                f"'model' extent {tp}"
            )
        params = jax.device_put(params, llama_param_shardings(params, mesh))
        draft_params = jax.device_put(
            draft_params, layout.replicated(mesh)
        )
        prompt = jax.device_put(
            prompt, layout.activation_sharding(mesh, "prompt")
        )
    run = _build_speculative(
        model,
        draft_model,
        b,
        s,
        max_new_tokens,
        int(k),
        None if eos_id is None else int(eos_id),
        mixed=prompt_lengths is not None,
        mesh=mesh,
        temperature=float(temperature),
    )
    if mesh is not None:
        rng = jax.device_put(rng, layout.replicated(mesh))
    if prompt_lengths is None:
        return run(params, draft_params, prompt, rng)
    lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if lengths.shape != (b,):
        raise ValueError(
            f"prompt_lengths must have shape ({b},), got {lengths.shape}"
        )
    import numpy as _np

    host = _np.asarray(lengths)
    if (host < 1).any() or (host > s).any():
        raise ValueError(
            f"prompt_lengths must be in [1, {s}] (the padded prompt "
            f"width); got {host.tolist()}"
        )
    if mesh is not None:
        lengths = jax.device_put(
            lengths, layout.activation_sharding(mesh, "per_row")
        )
    return run(params, draft_params, prompt, rng, lengths)


@functools.lru_cache(maxsize=16)
def _build_speculative(
    model, draft_model, b, s, max_new_tokens, k, eos_id, mixed=False,
    mesh=None, temperature=0.0,
):
    """Compile-once body per (models, shapes, k, eos, temperature)."""
    sampled = temperature > 0.0

    def greedy(logits):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def probs_of(logits):
        return jax.nn.softmax(
            logits.astype(jnp.float32) / temperature, axis=-1
        )

    def pick_first(logits, key):
        # the first emitted token comes from the target alone
        if not sampled:
            return greedy(logits)
        return jax.random.categorical(
            key, logits.astype(jnp.float32) / temperature
        ).astype(jnp.int32)

    def constrain(cache, tp_sharded):
        # pin both KV caches at the loop boundary: the target's like
        # generate's mesh path (batch on 'data', heads on 'model'), the
        # draft's batch-sharded only (its weights are replicated —
        # layout.decode_cache_spec(tp=False) drops the head axis)
        if mesh is None:
            return cache
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, layout.decode_cache_sharding(mesh, x, tp=tp_sharded)
            ),
            cache,
        )

    @jax.jit
    def run(params, draft_params, prompt, rng, lengths=None):
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        # Prefill BOTH caches on the prompt. padded=True everywhere:
        # slots are positions, which is what lets per-row acceptance
        # advance rows independently.
        t_logits, t_prefill = model.apply(
            {"params": params},
            prompt,
            positions=positions,
            decode=True,
            padded=True,
            mutable=["cache"],
        )
        _, d_prefill = draft_model.apply(
            {"params": draft_params},
            prompt,
            positions=positions,
            decode=True,
            padded=True,
            mutable=["cache"],
        )
        # first token: the target's own greedy pick at each row's last
        # REAL prompt position (cache invariant from here on: `last` is
        # NOT in either cache; `pos` is the next position to fill).
        # Mixed-length rows: the pad-slot garbage a full-width prefill
        # writes past a row's true length is only ever attended after
        # being overwritten by that row's real tokens (write-before-
        # attend + query position == write position), exactly as in
        # ``generate``'s padded path.
        rng, key0 = jax.random.split(rng)
        if mixed:
            last = pick_first(
                jnp.take_along_axis(
                    t_logits, (lengths - 1)[:, None, None], axis=1
                )[:, 0],
                key0,
            )
            pos0 = lengths + 1
        else:
            last = pick_first(t_logits[:, -1], key0)
            pos0 = jnp.full((b,), s + 1, jnp.int32)
        fill = eos_id if eos_id is not None else 0
        buf = jnp.full((b, max_new_tokens), fill, jnp.int32)
        buf = buf.at[:, 0].set(last)
        done = (
            (last == eos_id)
            if eos_id is not None
            else jnp.zeros((b,), bool)
        )
        n_out = jnp.ones((b,), jnp.int32)

        def draft_step(cache, tok, pos, key=None):
            logits, updated = draft_model.apply(
                {"params": draft_params, "cache": cache},
                tok[:, None],
                positions=pos[:, None],
                decode=True,
                padded=True,
                mutable=["cache"],
            )
            logits = logits[:, -1]
            if not sampled:
                return updated["cache"], greedy(logits), None
            nxt = jax.random.categorical(
                key, logits.astype(jnp.float32) / temperature
            ).astype(jnp.int32)
            return updated["cache"], nxt, probs_of(logits)

        def cond(carry):
            _, _, _, _, n_out, done, _, _ = carry
            return ~jnp.all(done | (n_out >= max_new_tokens))

        def body(carry):
            t_cache, d_cache, last, pos, n_out, done, buf, rng = carry
            rng, key_draft, key_verify = jax.random.split(rng, 3)

            # --- draft k tokens sequentially -------------------------
            def dstep(c, xs):
                d_cache, tok = c
                j, key = xs
                d_cache, nxt, q = draft_step(
                    d_cache, tok, pos - 1 + j, key
                )
                return (d_cache, nxt), (nxt, q)

            draft_keys = jax.random.split(key_draft, k)
            (d_cache, _), (drafts, d_probs) = jax.lax.scan(
                dstep,
                (d_cache, last),
                (jnp.arange(k, dtype=jnp.int32), draft_keys),
            )
            drafts = jnp.swapaxes(drafts, 0, 1)  # (B, k)
            if sampled:
                d_probs = jnp.swapaxes(d_probs, 0, 1)  # (B, k, V)
            # feed the draft its own final proposal: when all k are
            # accepted the next iteration queries slot pos+k-1, which
            # only this write fills (an unwritten slot would silently
            # degrade the NEXT round's proposals — never correctness,
            # which the target alone decides)
            d_cache, _, _ = draft_step(
                d_cache, drafts[:, -1], pos - 1 + k, draft_keys[-1]
            )
            d_cache = constrain(d_cache, tp_sharded=False)

            # --- one target forward over [last, drafts[:-1]] ---------
            # logits[:, j] predicts the token at position pos+j
            verify_in = jnp.concatenate([last[:, None], drafts], axis=1)[
                :, : k + 1
            ]
            vpos = pos[:, None] - 1 + jnp.arange(k + 1, dtype=jnp.int32)
            t_logits, t_upd = model.apply(
                {"params": params, "cache": t_cache},
                verify_in,
                positions=vpos,
                decode=True,
                padded=True,
                mutable=["cache"],
            )
            t_cache = constrain(t_upd["cache"], tp_sharded=True)
            if sampled:
                # rejection-sampling verification: emitted tokens are
                # distributed exactly as target-only sampling
                emit, accepted = speculative_accept(
                    key_verify, probs_of(t_logits), d_probs, drafts
                )
            else:
                t_pick = greedy(t_logits)  # (B, k+1) target's choices
                # accepted = longest prefix where draft == target pick;
                # emitted tokens are target picks throughout (positions
                # 0..a-1 equal the drafts there, position a is the
                # correction / bonus) — which is WHY output == plain
                # greedy
                match = t_pick[:, :k] == drafts  # (B, k)
                accepted = jnp.sum(
                    jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
                )  # (B,) in [0, k]
                emit = t_pick  # (B, k+1)
            j_idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
            valid = j_idx <= accepted[:, None]

            if eos_id is not None:
                # nothing after a row's first EOS is emitted
                before_eos = (
                    jnp.cumsum((emit == eos_id).astype(jnp.int32), axis=1)
                    - (emit == eos_id).astype(jnp.int32)
                ) == 0
                valid &= before_eos
            valid &= ~done[:, None]

            # scatter this iteration's tokens at per-row offsets;
            # out-of-range (row full) writes drop
            rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, k + 1))
            cols = jnp.where(
                valid, n_out[:, None] + j_idx, max_new_tokens
            )
            buf = buf.at[rows, cols].set(emit, mode="drop")

            emitted = jnp.sum(valid.astype(jnp.int32), axis=1)
            if eos_id is not None:
                done = done | jnp.any((emit == eos_id) & valid, axis=1)
            n_out_new = jnp.minimum(n_out + emitted, max_new_tokens)
            done = done | (n_out_new >= max_new_tokens)

            # next `last` = the last token this row emitted (the
            # correction, or the last pre-EOS token for finishing
            # rows); frozen rows keep their state
            last_j = jnp.maximum(emitted - 1, 0)
            new_last = jnp.take_along_axis(
                emit, last_j[:, None], axis=1
            )[:, 0]
            step_rows = emitted > 0
            last = jnp.where(step_rows, new_last, last)
            pos = jnp.where(done, pos, pos + emitted)
            n_out = n_out_new
            return (t_cache, d_cache, last, pos, n_out, done, buf, rng)

        carry = (
            constrain(t_prefill["cache"], tp_sharded=True),
            constrain(d_prefill["cache"], tp_sharded=False),
            last, pos0, n_out, done, buf, rng,
        )
        carry = jax.lax.while_loop(cond, body, carry)
        return carry[6]

    return run
