"""Speculative decoding — draft-and-verify greedy generation.

A small DRAFT model proposes ``k`` tokens autoregressively; the TARGET
model scores all ``k+1`` positions in ONE forward and keeps the longest
prefix it agrees with plus its own correction token. Greedy speculative
decoding emits EXACTLY the target model's greedy sequence (the
acceptance rule only ever keeps tokens the target itself would have
picked) — tested token-identically against :func:`...llama.generate`.

Why it wins on TPU: single-token decode is HBM-bandwidth-bound — every
step reads every weight once. Verification reads the target weights
once per ``a+1`` emitted tokens (``a`` = accepted drafts), and the
(B, k+1) verify forward is a better MXU shape than k+1 single-token
steps. Net speedup ≈ (accepted+1) / (k·cost_draft/cost_target + 1).

Cache discipline (no rollback needed): both models run their KV caches
through the per-row scatter path (``padded=True``), where a token's
slot IS its position and writes land BEFORE attention in each forward
(``llama.py:_cached_attention``). Rejected drafts leave stale cache
entries only at positions ≥ the next iteration's write window, and
every such slot is overwritten by that window before any query's
position reaches it — so acceptance just moves the position counters.

Reference parity note: the reference had no decode path at all
(SURVEY.md §2.2 — its serving story was per-executor SavedModel
replay); this module is capability beyond the reference, built on the
same KV-cache machinery as :func:`...llama.generate`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["speculative_generate"]


def speculative_generate(
    model,
    params,
    draft_model,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    k: int = 4,
    eos_id: int | None = None,
    prompt_lengths: jax.Array | None = None,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Greedy speculative decode: (B, S) int32 -> (B, max_new_tokens).

    Token-for-token identical to ``generate(model, params, prompt,
    max_new_tokens, eos_id=...)`` (greedy) for ANY draft model — the
    draft only changes speed, never output. ``k`` is the number of
    draft proposals per verification; both models need
    ``max_seq_len >= S + max_new_tokens + k`` (the verify window may
    scratch up to ``k`` slots past the emitted text). Rows finish
    independently on ``eos_id`` and the loop exits early once every
    row is done. Mixed-length prompts: RIGHT-pad and pass
    ``prompt_lengths`` (B,), exactly like ``generate``.

    ``mesh``: the TARGET runs TP/DP-sharded exactly like ``generate``'s
    mesh path (weights on 'model', batch + caches on 'data'); the DRAFT
    is fully replicated with only its batch/cache sharded on 'data' —
    a draft is small by construction, and replication frees it from the
    target's head-divisibility constraints.
    """
    b, s = prompt.shape
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    for name, cfg in (("model", model.cfg), ("draft_model", draft_model.cfg)):
        if s + max_new_tokens + k > cfg.max_seq_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + k "
                f"({k}) exceeds {name}.cfg.max_seq_len ({cfg.max_seq_len})"
            )
    if mesh is not None:
        from tensorflowonspark_tpu.models.llama import llama_param_shardings

        dp = mesh.shape["data"]
        tp = mesh.shape["model"]
        if b % dp:
            raise ValueError(
                f"batch {b} not divisible by the mesh 'data' extent {dp}"
            )
        if model.cfg.num_kv_heads % tp or model.cfg.num_heads % tp:
            raise ValueError(
                f"target heads ({model.cfg.num_heads}/"
                f"{model.cfg.num_kv_heads} kv) not divisible by the mesh "
                f"'model' extent {tp}"
            )
        params = jax.device_put(params, llama_param_shardings(params, mesh))
        draft_params = jax.device_put(
            draft_params, NamedSharding(mesh, P())
        )
        prompt = jax.device_put(prompt, NamedSharding(mesh, P("data", None)))
    run = _build_speculative(
        model,
        draft_model,
        b,
        s,
        max_new_tokens,
        int(k),
        None if eos_id is None else int(eos_id),
        mixed=prompt_lengths is not None,
        mesh=mesh,
    )
    if prompt_lengths is None:
        return run(params, draft_params, prompt)
    lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if lengths.shape != (b,):
        raise ValueError(
            f"prompt_lengths must have shape ({b},), got {lengths.shape}"
        )
    import numpy as _np

    host = _np.asarray(lengths)
    if (host < 1).any() or (host > s).any():
        raise ValueError(
            f"prompt_lengths must be in [1, {s}] (the padded prompt "
            f"width); got {host.tolist()}"
        )
    if mesh is not None:
        lengths = jax.device_put(lengths, NamedSharding(mesh, P("data")))
    return run(params, draft_params, prompt, lengths)


@functools.lru_cache(maxsize=16)
def _build_speculative(
    model, draft_model, b, s, max_new_tokens, k, eos_id, mixed=False,
    mesh=None,
):
    """Compile-once body per (models, shapes, k, eos)."""

    def greedy(logits):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def constrain(cache, tp_sharded):
        # pin both KV caches at the loop boundary: the target's like
        # generate's mesh path (batch on 'data', heads on 'model'), the
        # draft's batch-sharded only (its weights are replicated)
        if mesh is None:
            return cache
        from tensorflowonspark_tpu.models.llama import decode_cache_spec

        def spec(x):
            sp = decode_cache_spec(x)
            if not tp_sharded and x.ndim == 4:
                sp = P("data", None, None, None)
            return NamedSharding(mesh, sp)

        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, spec(x)), cache
        )

    @jax.jit
    def run(params, draft_params, prompt, lengths=None):
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        # Prefill BOTH caches on the prompt. padded=True everywhere:
        # slots are positions, which is what lets per-row acceptance
        # advance rows independently.
        t_logits, t_prefill = model.apply(
            {"params": params},
            prompt,
            positions=positions,
            decode=True,
            padded=True,
            mutable=["cache"],
        )
        _, d_prefill = draft_model.apply(
            {"params": draft_params},
            prompt,
            positions=positions,
            decode=True,
            padded=True,
            mutable=["cache"],
        )
        # first token: the target's own greedy pick at each row's last
        # REAL prompt position (cache invariant from here on: `last` is
        # NOT in either cache; `pos` is the next position to fill).
        # Mixed-length rows: the pad-slot garbage a full-width prefill
        # writes past a row's true length is only ever attended after
        # being overwritten by that row's real tokens (write-before-
        # attend + query position == write position), exactly as in
        # ``generate``'s padded path.
        if mixed:
            last = greedy(
                jnp.take_along_axis(
                    t_logits, (lengths - 1)[:, None, None], axis=1
                )[:, 0]
            )
            pos0 = lengths + 1
        else:
            last = greedy(t_logits[:, -1])
            pos0 = jnp.full((b,), s + 1, jnp.int32)
        fill = eos_id if eos_id is not None else 0
        buf = jnp.full((b, max_new_tokens), fill, jnp.int32)
        buf = buf.at[:, 0].set(last)
        done = (
            (last == eos_id)
            if eos_id is not None
            else jnp.zeros((b,), bool)
        )
        n_out = jnp.ones((b,), jnp.int32)

        def draft_step(cache, tok, pos):
            logits, updated = draft_model.apply(
                {"params": draft_params, "cache": cache},
                tok[:, None],
                positions=pos[:, None],
                decode=True,
                padded=True,
                mutable=["cache"],
            )
            return updated["cache"], greedy(logits[:, -1])

        def cond(carry):
            _, _, _, _, n_out, done, _ = carry
            return ~jnp.all(done | (n_out >= max_new_tokens))

        def body(carry):
            t_cache, d_cache, last, pos, n_out, done, buf = carry

            # --- draft k tokens sequentially -------------------------
            def dstep(c, j):
                d_cache, tok = c
                d_cache, nxt = draft_step(d_cache, tok, pos - 1 + j)
                return (d_cache, nxt), nxt

            (d_cache, _), drafts = jax.lax.scan(
                dstep, (d_cache, last), jnp.arange(k, dtype=jnp.int32)
            )
            drafts = jnp.swapaxes(drafts, 0, 1)  # (B, k)
            # feed the draft its own final proposal: when all k are
            # accepted the next iteration queries slot pos+k-1, which
            # only this write fills (an unwritten slot would silently
            # degrade the NEXT round's proposals — never correctness,
            # which the target alone decides)
            d_cache, _ = draft_step(d_cache, drafts[:, -1], pos - 1 + k)
            d_cache = constrain(d_cache, tp_sharded=False)

            # --- one target forward over [last, drafts[:-1]] ---------
            # logits[:, j] predicts the token at position pos+j
            verify_in = jnp.concatenate([last[:, None], drafts], axis=1)[
                :, : k + 1
            ]
            vpos = pos[:, None] - 1 + jnp.arange(k + 1, dtype=jnp.int32)
            t_logits, t_upd = model.apply(
                {"params": params, "cache": t_cache},
                verify_in,
                positions=vpos,
                decode=True,
                padded=True,
                mutable=["cache"],
            )
            t_cache = constrain(t_upd["cache"], tp_sharded=True)
            t_pick = greedy(t_logits)  # (B, k+1) target's own choices

            # accepted = longest prefix where draft == target pick;
            # emitted tokens are target picks throughout (positions
            # 0..a-1 equal the drafts there, position a is the
            # correction / bonus) — which is WHY output == plain greedy
            match = t_pick[:, :k] == drafts  # (B, k)
            accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                               axis=1)  # (B,) in [0, k]
            emit = t_pick  # (B, k+1)
            j_idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
            valid = j_idx <= accepted[:, None]

            if eos_id is not None:
                # nothing after a row's first EOS is emitted
                before_eos = (
                    jnp.cumsum((emit == eos_id).astype(jnp.int32), axis=1)
                    - (emit == eos_id).astype(jnp.int32)
                ) == 0
                valid &= before_eos
            valid &= ~done[:, None]

            # scatter this iteration's tokens at per-row offsets;
            # out-of-range (row full) writes drop
            rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, k + 1))
            cols = jnp.where(
                valid, n_out[:, None] + j_idx, max_new_tokens
            )
            buf = buf.at[rows, cols].set(emit, mode="drop")

            emitted = jnp.sum(valid.astype(jnp.int32), axis=1)
            if eos_id is not None:
                done = done | jnp.any((emit == eos_id) & valid, axis=1)
            n_out_new = jnp.minimum(n_out + emitted, max_new_tokens)
            done = done | (n_out_new >= max_new_tokens)

            # next `last` = the last token this row emitted (the
            # correction, or the last pre-EOS token for finishing
            # rows); frozen rows keep their state
            last_j = jnp.maximum(emitted - 1, 0)
            new_last = jnp.take_along_axis(
                emit, last_j[:, None], axis=1
            )[:, 0]
            step_rows = emitted > 0
            last = jnp.where(step_rows, new_last, last)
            pos = jnp.where(done, pos, pos + emitted)
            n_out = n_out_new
            return (t_cache, d_cache, last, pos, n_out, done, buf)

        carry = (
            constrain(t_prefill["cache"], tp_sharded=True),
            constrain(d_prefill["cache"], tp_sharded=False),
            last, pos0, n_out, done, buf,
        )
        carry = jax.lax.while_loop(cond, body, carry)
        return carry[6]

    return run
