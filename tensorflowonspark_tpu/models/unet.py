"""U-Net for semantic segmentation.

Parity note: the reference ships ``examples/segmentation`` — a TF2 port of
the TensorFlow image-segmentation tutorial (U-Net over Oxford-IIIT Pet,
InputMode.TENSORFLOW; SURVEY.md §2.4). This is the model family behind the
rebuild's segmentation example, written from scratch for TPU.

TPU-first design notes:

- NHWC, convs in bf16 (MXU), GroupNorm in fp32. GroupNorm instead of the
  tutorial's BatchNorm: no cross-replica batch statistics, so the model is
  indifferent to how the batch is sharded over the mesh.
- Resolution halves via strided conv, doubles via ``jax.image.resize`` +
  conv (resize-conv avoids transposed-conv checkerboarding and lowers to
  clean XLA gathers).
- Static depth/width from config — the stage stack unrolls at trace time.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflowonspark_tpu.compute import layout


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    features: tuple[int, ...] = (64, 128, 256, 512)  # encoder widths
    bottleneck_features: int = 1024
    num_classes: int = 3  # pet tutorial: foreground/background/outline
    dtype: jnp.dtype = jnp.bfloat16

    @staticmethod
    def tiny(**overrides) -> "UNetConfig":
        base = dict(features=(8, 16), bottleneck_features=32, num_classes=3)
        base.update(overrides)
        return UNetConfig(**base)


class _ConvBlock(nn.Module):
    features: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.Conv(
                self.features, (3, 3), padding="SAME", use_bias=False,
                dtype=self.dtype,
            )(x)
            # Norm in fp32; group count capped for thin test-size widths.
            x = nn.GroupNorm(
                num_groups=min(8, self.features), dtype=jnp.float32
            )(x)
            x = nn.relu(x).astype(self.dtype)
        return x


class UNet(nn.Module):
    config: UNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = x.astype(cfg.dtype)
        skips = []
        for f in cfg.features:
            x = _ConvBlock(f, cfg.dtype)(x)
            skips.append(x)
            x = nn.Conv(  # strided downsample
                f, (3, 3), strides=(2, 2), padding="SAME", dtype=cfg.dtype
            )(x)
        x = _ConvBlock(cfg.bottleneck_features, cfg.dtype)(x)
        for f, skip in zip(reversed(cfg.features), reversed(skips)):
            n, h, w, _ = skip.shape
            x = jax.image.resize(x, (n, h, w, x.shape[-1]), "nearest")
            x = nn.Conv(f, (3, 3), padding="SAME", dtype=cfg.dtype)(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = _ConvBlock(f, cfg.dtype)(x)
        # Per-pixel logits in fp32 for a stable softmax.
        return nn.Conv(cfg.num_classes, (1, 1), dtype=jnp.float32)(x)


def unet_param_shardings(params, mesh: Mesh):
    """FSDP rules: shard conv kernels' output channels over 'fsdp' where
    divisible; replicate norm scale/bias (tiny) — the declarative
    'unet' table in :mod:`tensorflowonspark_tpu.compute.layout`."""
    return layout.param_shardings(params, mesh, "unet")


def loss_fn(model: UNet):
    """Build ``loss(params, batch) -> loss`` for batches
    {'image': (n,h,w,c), 'mask': (n,h,w) int}: mean per-pixel softmax CE."""
    import optax

    def loss(params, batch):
        logits = model.apply({"params": params}, batch["image"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["mask"]
        ).mean()

    return loss


def iou(model: UNet, params, batch, num_classes: int) -> jax.Array:
    """Mean intersection-over-union across classes (eval metric)."""
    pred = jnp.argmax(
        model.apply({"params": params}, batch["image"]), axis=-1
    )
    mask = batch["mask"]
    ious = []
    for c in range(num_classes):
        inter = jnp.sum((pred == c) & (mask == c))
        union = jnp.sum((pred == c) | (mask == c))
        ious.append(jnp.where(union > 0, inter / union, 1.0))
    return jnp.mean(jnp.stack(ious))
