"""LK003: lock-acquisition-order cycles; TH001: unjoinable threads.

Half of the tfsan static head (``tools/tfsan.py``; the other half is
:mod:`.blocking`). Every catastrophic concurrency bug this repo has hit
— the wedged-node authkey hang, the shm-ring view-pinned-while-blocking
deadlocks, the unlocked ``_ring_cache`` read — lived in pure-Python
threading code that LK001's per-attribute discipline cannot see, because
the defect is not *which* lock guards state but the *order* locks are
taken in and what runs while they are held.

**LK003 (lock-order cycles).** Nested ``with <lock>:`` scopes define
acquisition-order edges: acquiring B while holding A asserts "A before
B". Edges are collected lexically per function AND across the package
call graph (reusing :mod:`.hostsync`'s walker: a function that acquires
B — directly or transitively — called from under A adds the same A→B
edge). A cycle in the resulting directed graph is a potential ABBA
deadlock: two threads entering the cycle from different nodes can each
hold what the other needs, forever. Self-edges (re-acquiring the lock
you hold) are flagged only when the lock is provably a non-reentrant
``threading.Lock`` — ``with self._lock:`` nested under itself via an
``RLock`` is legal reentrance.

Lock identity is the *annotation-grade* name, not the object: within a
class, ``self._lock`` keys as ``<module>::<Class>._lock``; module
globals as ``<module>::<name>``; other bases textually. Distinct
instances of one class share a key deliberately — the checker reasons
about lock *roles* (every ``Registry._lock``), the same aggregation the
kernel's lockdep uses, because an order inversion between two instances
of the same role is exactly the two-object ABBA shape.

**TH001 (unjoinable threads).** A non-daemon ``threading.Thread`` that
is never ``join(timeout=...)``-ed can hang process exit forever (the
PR-4 wedged-node class: the interpreter waits on a thread blocked on a
dead peer). Every non-daemon thread must either be joined *with a
timeout* somewhere in its module, or be daemonized. A bare ``join()``
does not count: an unbounded join IS the hang.

Escapes (trailing comment on the acquisition / constructor line):

- ``# lint: lock-order-ok`` — this acquisition's edges are exempt
  (a documented hierarchy violation with its own synchronization).
- ``# lint: thread-ok`` — the thread is joined indirectly (a helper
  owns the join) or its liveness is bounded elsewhere.
"""

from __future__ import annotations

import ast
import re

from tensorflowonspark_tpu.analysis.core import Finding, Module, Package
from tensorflowonspark_tpu.analysis.hostsync import _build_graph

ORDER_OK_RE = re.compile(r"#\s*lint:\s*lock-order-ok\b")
THREAD_OK_RE = re.compile(r"#\s*lint:\s*thread-ok\b")

# A with-context expression is lock-like when its final name component
# looks like a lock/condition role name. Matches the repo's actual
# conventions (_lock, _submit_lock, _metrics_lock, _cond, _cv); a
# factory call (`with open(...)`) is never lock-like.
LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|locks|mutex|mu)$|(?:^|_)(?:cond|cv)$")

__all__ = ["check_lock_order", "check_threads", "lock_key", "LOCKISH_RE"]


def _line_has(mod: Module, node: ast.AST, pattern: re.Pattern) -> bool:
    end = getattr(node, "end_lineno", node.lineno) or node.lineno
    # for compound statements the escape must sit on the HEADER lines,
    # not anywhere in the body (a with-block's end_lineno spans it all)
    body = getattr(node, "body", None)
    if isinstance(body, list) and body and hasattr(body[0], "lineno"):
        end = min(end, body[0].lineno - 1)
    end = max(end, node.lineno)
    for line in range(node.lineno, end + 1):
        c = mod.comments.get(line)
        if c and pattern.search(c):
            return True
    return False


def lock_key(mod: Module, cls: str | None, expr: ast.AST) -> str | None:
    """Stable role name for a lock-valued with-context expression, or
    None when the expression is not lock-like."""
    if isinstance(expr, ast.Name):
        if LOCKISH_RE.search(expr.id):
            return f"{mod.relpath}::{expr.id}"
        return None
    if isinstance(expr, ast.Attribute):
        if not LOCKISH_RE.search(expr.attr):
            return None
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            owner = cls or "?"
            return f"{mod.relpath}::{owner}.{expr.attr}"
        try:
            base = ast.unparse(expr.value)
        except Exception:  # pragma: no cover - unparse is total
            return None
        return f"{mod.relpath}::{base}.{expr.attr}"
    return None


def _lock_kinds(pkg: Package) -> dict:
    """{lock_key: 'Lock'|'RLock'|'Condition'} from creation sites
    (``<target> = threading.Lock()`` and friends). Unlisted keys have
    unknown kind — self-edges on them are not judged."""
    kinds: dict = {}

    def note(mod, cls, target, call):
        root = None
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id in ("threading", "_thread"):
                root = f.attr
        elif isinstance(f, ast.Name):
            if f.id in ("Lock", "RLock", "Condition"):
                root = f.id
        if root not in ("Lock", "RLock", "Condition"):
            return
        key = lock_key(mod, cls, target)
        if key is not None:
            kinds[key] = root

    for mod in pkg.modules:

        def walk(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                    continue
                if isinstance(child, ast.Assign) and isinstance(
                    child.value, ast.Call
                ):
                    for t in child.targets:
                        note(mod, cls, t, child.value)
                elif isinstance(child, ast.AnnAssign) and isinstance(
                    child.value, ast.Call
                ):
                    note(mod, cls, child.target, child.value)
                walk(child, cls)

        walk(mod.tree, None)
    return kinds


class _FnScan(ast.NodeVisitor):
    """One function's lock behavior: direct acquisition-order edges,
    the set of locks acquired anywhere in it, and every call made while
    at least one lock is lexically held."""

    def __init__(self, mod: Module, cls: str | None):
        self.mod = mod
        self.cls = cls
        self.edges: list = []  # (held_key, acquired_key, line, col)
        self.acquired: dict = {}  # key -> first (line, col)
        self.held_calls: list = []  # (call_node, tuple(held_keys))
        self.self_edges: list = []  # (key, line, col) Lock-reacquire shape
        self._held: list = []

    def _visit_fn(self, node):
        # Nested defs run later, without the enclosing with-blocks held
        # — and they are indexed as their own functions (hostsync
        # qualnames), so they are scanned separately. Recursing here
        # would double-count their edges AND wrongly attribute a
        # deferred callback's acquisitions to this function's
        # transitive-acquire set (the deferred-race shape).
        pass

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn
    visit_Lambda = _visit_fn

    def visit_With(self, node):
        exempt = _line_has(self.mod, node, ORDER_OK_RE)
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            key = lock_key(self.mod, self.cls, item.context_expr)
            if key is None or exempt:
                continue
            if key not in self.acquired:
                self.acquired[key] = (node.lineno, node.col_offset)
            for held in self._held:
                if held == key:
                    self.self_edges.append(
                        (key, node.lineno, node.col_offset)
                    )
                else:
                    self.edges.append(
                        (held, key, node.lineno, node.col_offset)
                    )
            self._held.append(key)
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        if pushed:
            del self._held[-pushed:]

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        if self._held:
            self.held_calls.append((node, tuple(self._held)))
        self.generic_visit(node)


def scan_functions(pkg: Package):
    """{func_key: _FnScan} over every indexed function, plus the call
    graph — shared between this module and :mod:`.blocking` so the
    package is walked once per tfsan pass."""
    all_funcs, call_edges = _build_graph(pkg)
    scans: dict = {}
    for key, info in all_funcs.items():
        scan = _FnScan(info.mod, info.cls)
        # scan only the function's own body; nested defs are their own
        # entries (visit_FunctionDef resets held state anyway)
        for stmt in info.node.body:
            scan.visit(stmt)
        scans[key] = scan
    return all_funcs, call_edges, scans


def _transitive_acquires(call_edges: dict, scans: dict) -> dict:
    """Fixpoint: locks acquired by a function or anything it calls."""
    acq = {k: set(s.acquired) for k, s in scans.items()}
    changed = True
    while changed:
        changed = False
        for key, targets in call_edges.items():
            mine = acq.setdefault(key, set())
            before = len(mine)
            for t in targets:
                mine |= acq.get(t, set())
            if len(mine) != before:
                changed = True
    return acq


def _call_targets(call, call_edges, key):
    """Resolved callee keys for one call node — the subset of this
    function's call-graph edges the call expression can name."""
    names = set()
    f = call.func
    if isinstance(f, ast.Name):
        names.add(f.id)
    elif isinstance(f, ast.Attribute):
        names.add(f.attr)
    out = []
    for t in call_edges.get(key, ()):
        if t[1].rsplit(".", 1)[-1] in names:
            out.append(t)
    return out


def _find_cycles(graph: dict) -> list:
    """Elementary cycles grouped per SCC (Tarjan), each reported once:
    the cycle is rotated to start at its smallest node so the finding
    message — the baseline key — is deterministic."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    cycles = []
    for comp in sccs:
        # one representative cycle through the SCC: walk from the
        # smallest node along in-SCC edges until it closes
        start = comp[0]
        comp_set = set(comp)
        path = [start]
        seen = {start}
        node = start
        while True:
            nxt = None
            for w in sorted(graph.get(node, ())):
                if w in comp_set:
                    nxt = w
                    break
            if nxt is None or nxt == start:
                break
            if nxt in seen:
                # trim to the sub-cycle through nxt
                path = path[path.index(nxt):]
                break
            path.append(nxt)
            seen.add(nxt)
            node = nxt
        cycles.append(path)
    return cycles


def check_lock_order(pkg: Package, shared=None) -> list:
    """LK003 over the whole package. ``shared`` is the optional
    ``(all_funcs, call_edges, scans)`` triple from :func:`scan_functions`
    so one walk serves both tfsan static rules."""
    all_funcs, call_edges, scans = shared or scan_functions(pkg)
    kinds = _lock_kinds(pkg)

    graph: dict = {}
    sites: dict = {}  # (a, b) -> (relpath, line, col)

    def add_edge(a, b, rel, line, col):
        graph.setdefault(a, set()).add(b)
        key = (a, b)
        if key not in sites or (rel, line) < sites[key][:2]:
            sites[key] = (rel, line, col)

    for key, scan in scans.items():
        for a, b, line, col in scan.edges:
            add_edge(a, b, scan.mod.relpath, line, col)

    # call-graph propagation: a call under held locks H reaching a
    # callee that (transitively) acquires B adds every H→B edge
    acq = _transitive_acquires(call_edges, scans)
    for key, scan in scans.items():
        for call, held in scan.held_calls:
            for target in _call_targets(call, call_edges, key):
                for b in acq.get(target, ()):
                    for a in held:
                        if a != b:
                            add_edge(
                                a, b, scan.mod.relpath,
                                call.lineno, call.col_offset,
                            )

    findings: list = []

    def short(key):
        return key.split("::", 1)[1] if "::" in key else key

    for cycle in _find_cycles(graph):
        ring = cycle + [cycle[0]]
        edge_bits = []
        for a, b in zip(ring, ring[1:]):
            rel, line, _col = sites.get((a, b), ("?", 0, 0))
            edge_bits.append(f"{short(a)}->{short(b)} at {rel}:{line}")
        anchor = sites.get((ring[0], ring[1]), ("?", 0, 0))
        findings.append(
            Finding(
                "LK003",
                anchor[0],
                anchor[1],
                anchor[2],
                "lock-order cycle (potential ABBA deadlock): "
                + " -> ".join(short(k) for k in ring)
                + "; " + "; ".join(edge_bits),
            )
        )

    # non-reentrant self-acquisition: with self._lock: ... with
    # self._lock: — an instant self-deadlock when the lock is a plain
    # threading.Lock (RLock/Condition reentrance is legal)
    for key, scan in scans.items():
        for lkey, line, col in scan.self_edges:
            if kinds.get(lkey) == "Lock":
                findings.append(
                    Finding(
                        "LK003",
                        scan.mod.relpath,
                        line,
                        col,
                        f"re-acquisition of non-reentrant lock "
                        f"'{short(lkey)}' already held in this scope "
                        "(self-deadlock; use an RLock or restructure)",
                    )
                )
    return findings


# -- TH001 -------------------------------------------------------------------


def _is_true(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return isinstance(f.value, ast.Name) and f.value.id == "threading"
    return isinstance(f, ast.Name) and f.id == "Thread"


def check_threads(pkg: Package) -> list:
    """TH001: every non-daemon ``threading.Thread`` construction must
    have a module-visible ``<target>.join(<timeout>)`` — daemonize it or
    bound its join."""
    findings: list = []
    for mod in pkg.modules:
        # pass 1: names (attr or local) with a timeout-bounded join, and
        # names daemonized after construction (t.daemon = True)
        joined: set = set()
        daemonized: set = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "join"
                    and (
                        node.args
                        or any(k.arg == "timeout" for k in node.keywords)
                    )
                    and isinstance(f.value, (ast.Name, ast.Attribute))
                ):
                    tgt = f.value
                    joined.add(
                        tgt.attr if isinstance(tgt, ast.Attribute) else tgt.id
                    )
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr == "daemon"
                        and _is_true(node.value)
                        and isinstance(t.value, (ast.Name, ast.Attribute))
                    ):
                        base = t.value
                        daemonized.add(
                            base.attr
                            if isinstance(base, ast.Attribute)
                            else base.id
                        )
        # pass 2: judge each construction
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                ctor = None
                if isinstance(node, ast.Expr) and isinstance(
                    node.value, ast.Call
                ):
                    # unassigned: threading.Thread(...).start() chains
                    inner = node.value
                    while (
                        isinstance(inner, ast.Call)
                        and isinstance(inner.func, ast.Attribute)
                        and isinstance(inner.func.value, ast.Call)
                    ):
                        inner = inner.func.value
                    if isinstance(inner, ast.Call) and _thread_ctor(inner):
                        ctor = inner
                if ctor is None:
                    continue
                targets = []
            else:
                if not (
                    isinstance(node.value, ast.Call)
                    and _thread_ctor(node.value)
                ):
                    continue
                ctor = node.value
                targets = node.targets
            daemon_kw = next(
                (k.value for k in ctor.keywords if k.arg == "daemon"), None
            )
            if daemon_kw is not None:
                if _is_true(daemon_kw) or not isinstance(
                    daemon_kw, ast.Constant
                ):
                    # daemon=True, or daemon=<expr> (trusted: possibly
                    # True at runtime — zero-FP bias)
                    continue
            anchor = node if targets else ctor
            if _line_has(mod, anchor, THREAD_OK_RE):
                continue
            names = []
            for t in targets:
                if isinstance(t, ast.Attribute):
                    names.append(t.attr)
                elif isinstance(t, ast.Name):
                    names.append(t.id)
            if any(n in joined or n in daemonized for n in names):
                continue
            label = names[0] if names else "<unassigned>"
            findings.append(
                Finding(
                    "TH001",
                    mod.relpath,
                    anchor.lineno,
                    anchor.col_offset,
                    f"non-daemon thread '{label}' is never "
                    "join(timeout=...)-ed in this module: a wedged peer "
                    "hangs process exit forever — daemonize it or bound "
                    "the join",
                )
            )
    return findings
