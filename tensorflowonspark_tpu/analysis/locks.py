"""LK001: guarded attributes must be accessed under their lock.

Convention (docs/STATIC_ANALYSIS.md): the assignment that INTRODUCES a
piece of shared mutable state carries a trailing comment naming the lock
that guards it::

    self._closed = False  # guarded-by: self._submit_lock
    _ring_cache: dict = {}  # guarded-by: _ring_cache_lock

From then on, every lexical read or write of that attribute anywhere in
the module must sit inside a ``with <that lock>:`` block. The check is
LEXICAL (the ISSUE's commit-time bar), deliberately so: it cannot prove
the lock is the right one, but it catches the overwhelmingly common race
shape — a new call site touching shared state without taking the lock —
at parse time, with zero runtime cost.

Escapes, in order of preference:

- fix the call site (take the lock);
- ``# lint: holds-lock`` on the ``def`` line of a function whose CALLERS
  always hold the lock (callee of a locked region);
- ``# lint: lockfree-read: <justification>`` on the ACCESS line, for a
  deliberate lock-free read whose staleness is provably benign (the
  serving engine's stats()/drain-poll reads). The justification is
  mandatory — an empty one is its own finding (LK004) — so the "why it
  is safe" lives next to the read it excuses, reviewed with the code
  rather than rotting in a baseline file.

Scoping rules: the function containing the annotation (normally
``__init__``, where the object is not yet published) is exempt, as is
module top-level code for module-global guards (imports are
single-threaded). Guarded attributes are matched by NAME within their
module, and the lock requirement follows the accessing expression's
base: ``self._series`` needs ``with self._lock``, ``m._series`` needs
``with m._lock`` — so cross-object access in the same module (the
registry render path) checks correctly.
"""

from __future__ import annotations

import ast
import re

from tensorflowonspark_tpu.analysis.core import Finding, Module, Package

GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
HOLDS_RE = re.compile(r"#\s*lint:\s*holds-lock\b")
LOCKFREE_RE = re.compile(r"#\s*lint:\s*lockfree-read\b:?\s*(.*)")

__all__ = ["check", "GUARD_RE", "HOLDS_RE", "LOCKFREE_RE"]


def _stmt_comment(mod: Module, node: ast.stmt, pattern: re.Pattern):
    """First match of ``pattern`` in a comment on any line the statement
    spans (trailing same-line comments are the convention; a multiline
    assignment may carry it on any of its lines)."""
    end = getattr(node, "end_lineno", node.lineno) or node.lineno
    for line in range(node.lineno, end + 1):
        c = mod.comments.get(line)
        if c:
            m = pattern.search(c)
            if m:
                return m
    return None


def _def_has_marker(mod: Module, fn: ast.AST, pattern: re.Pattern) -> bool:
    """Marker comment anywhere between the ``def`` line and the first
    body statement (covers multi-line signatures)."""
    stop = fn.body[0].lineno if fn.body else fn.lineno
    for line in range(fn.lineno, stop + 1):
        c = mod.comments.get(line)
        if c and pattern.search(c):
            return True
    return False


class _GuardCollector(ast.NodeVisitor):
    """Pass 1: find ``# guarded-by`` annotations.

    attr_guards: {attr_name: (lock_text, annotating_function_node)}
    global_guards: {name: lock_text} (module top-level assignments)
    """

    def __init__(self, mod: Module):
        self.mod = mod
        self.attr_guards: dict = {}
        self.global_guards: dict = {}
        self.findings: list = []
        self._func_stack: list = []

    def visit_FunctionDef(self, node):
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _targets(self, node):
        if isinstance(node, (ast.Assign,)):
            return node.targets
        return [node.target]  # AnnAssign / AugAssign

    def _handle(self, node):
        m = _stmt_comment(self.mod, node, GUARD_RE)
        if not m:
            return self.generic_visit(node)
        lock = m.group(1)
        annotated = False
        for t in self._targets(node):
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                self.attr_guards[t.attr] = (
                    lock,
                    self._func_stack[-1] if self._func_stack else None,
                )
                annotated = True
            elif isinstance(t, ast.Name) and not self._func_stack:
                self.global_guards[t.id] = lock
                annotated = True
        if not annotated:
            self.findings.append(
                Finding(
                    "LK002",
                    self.mod.relpath,
                    node.lineno,
                    node.col_offset,
                    "guarded-by annotation must sit on a 'self.<attr> = "
                    "...' or module-level 'name = ...' assignment",
                )
            )
        self.generic_visit(node)

    visit_Assign = _handle
    visit_AnnAssign = _handle
    visit_AugAssign = _handle


class _AccessChecker(ast.NodeVisitor):
    """Pass 2: walk with a lexical stack of held locks; flag guarded
    accesses with no matching ``with`` in scope."""

    def __init__(self, mod: Module, collector: _GuardCollector):
        self.mod = mod
        self.c = collector
        self.findings: list = []
        self._locks: list = []  # unparsed lock exprs currently held
        self._exempt_depth = 0  # inside annotating fn or holds-lock fn
        self._in_function = 0

    # -- scope handling -----------------------------------------------

    def _visit_fn(self, node):
        exempt = _def_has_marker(self.mod, node, HOLDS_RE) or any(
            node is fn for _, fn in self.c.attr_guards.values()
        )
        self._exempt_depth += exempt
        self._in_function += 1
        # A nested def/lambda does NOT inherit the enclosing with-blocks:
        # its body runs when the function is CALLED, by which time the
        # lock is long released — the register-a-callback-under-lock
        # shape is exactly the deferred race this checker exists for.
        held, self._locks = self._locks, []
        self.generic_visit(node)
        self._locks = held
        self._in_function -= 1
        self._exempt_depth -= exempt

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Lambda(self, node):
        held, self._locks = self._locks, []
        self._in_function += 1
        self.generic_visit(node)
        self._in_function -= 1
        self._locks = held

    def visit_With(self, node):
        held = []
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            try:
                held.append(ast.unparse(item.context_expr))
            except Exception:  # pragma: no cover - unparse is total
                pass
        self._locks.extend(held)
        for stmt in node.body:
            self.visit(stmt)
        del self._locks[len(self._locks) - len(held):]

    visit_AsyncWith = visit_With

    # -- accesses ------------------------------------------------------

    def _flag(self, node, attr, required):
        # per-access escape: a justified deliberate lock-free READ.
        # Strictly reads — an unlocked WRITE to guarded state is a race
        # no staleness argument can justify, so a Store/Del access
        # falls through to LK001 even when the line carries the comment
        # (the runtime witness enforces the same asymmetry).
        is_read = isinstance(getattr(node, "ctx", None), ast.Load)
        c = self.mod.comments.get(node.lineno)
        m = LOCKFREE_RE.search(c) if c and is_read else None
        if m is not None:
            if m.group(1).strip():
                return  # justified: suppressed, reviewed in place
            self.findings.append(
                Finding(
                    "LK004",
                    self.mod.relpath,
                    node.lineno,
                    node.col_offset,
                    "'lint: lockfree-read' requires a justification "
                    "('# lint: lockfree-read: <why the stale read is "
                    "benign>')",
                )
            )
            return
        self.findings.append(
            Finding(
                "LK001",
                self.mod.relpath,
                node.lineno,
                node.col_offset,
                f"access of '{attr}' (guarded-by {required}) outside "
                f"'with {required}:'",
            )
        )

    def visit_Attribute(self, node):
        guard = self.c.attr_guards.get(node.attr)
        if guard is not None and self._exempt_depth == 0:
            lock, _fn = guard
            base = ast.unparse(node.value)
            required = (
                f"{base}.{lock.split('.', 1)[1]}"
                if lock.startswith("self.")
                else lock
            )
            if required not in self._locks:
                self._flag(node, f"{base}.{node.attr}", required)
        self.generic_visit(node)

    def visit_Name(self, node):
        lock = self.c.global_guards.get(node.id)
        if (
            lock is not None
            and self._exempt_depth == 0
            and self._in_function  # module top level is import-time
            and lock not in self._locks
        ):
            self._flag(node, node.id, lock)
        self.generic_visit(node)


def check(pkg: Package) -> list:
    findings: list = []
    for mod in pkg.modules:
        collector = _GuardCollector(mod)
        collector.visit(mod.tree)
        findings.extend(collector.findings)
        if not collector.attr_guards and not collector.global_guards:
            continue
        checker = _AccessChecker(mod, collector)
        # skip the annotation statements themselves for global guards:
        # handled by exempting module top-level Name accesses.
        checker.visit(mod.tree)
        findings.extend(checker.findings)
    return findings
