"""HS: implicit device→host syncs on hot paths; TL: tracer leaks.

**Hot paths.** A conservative package-wide call graph is built from the
ASTs (edges: same-module calls by name, ``self.method()`` within the
lexically enclosing class, and ``alias.func()`` through intra-package
imports) and walked from the configured roots — by default the serving
engine scheduler loop (``ContinuousBatcher._loop``) and the training
step builder (``build_train_step``). Everything reachable is "hot":
an implicit device sync there stalls the device pipeline the PR-2
scheduler exists to keep full.

**Device-value tracking** is a per-function, statement-ordered
approximation: a name assigned from a ``jnp.*``/``jax.*`` expression
(except the EXPLICIT fetch ``jax.device_get``) is device-resident; a
name re-assigned from ``np.*`` or ``jax.device_get`` becomes host. Only
expressions that provably mention a device value are flagged — unknown
names (parameters, loop targets) are NOT flagged, trading recall for a
near-zero false-positive rate, which is what keeps the baseline honest.

Rules:

- **HS001** — ``.item()`` anywhere in a hot function. ``.item()`` is a
  per-scalar blocking round-trip on jax arrays and a hidden scalar copy
  even on numpy; hot paths fetch in bulk (``jax.device_get``) instead.
- **HS002** — ``np.asarray``/``np.array`` over a device value in a hot
  function (an implicit transfer; spell it ``jax.device_get``).
- **HS003** — ``float()``/``int()``/``bool()`` over a device value in a
  hot function (implicit scalar sync).
- **TL001** — assignment to ``self.<attr>`` inside a ``jit``-decorated
  function: the traced value outlives its trace (the classic leaked-
  tracer bug; on recompile it poisons unrelated calls).
- **TL002** — assignment to a ``global``-declared name inside a
  ``jit``-decorated function, same failure mode.

``# lint: sync-ok`` on a ``def`` line suppresses HS rules for that
function — the annotation for DELIBERATE fetch points (the engine's
block fetch), kept next to the code they justify.
"""

from __future__ import annotations

import ast
import re

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package
from tensorflowonspark_tpu.analysis.locks import _def_has_marker

SYNC_OK_RE = re.compile(r"#\s*lint:\s*sync-ok\b")
_JIT_RE = re.compile(r"(?:^|[^\w.])jit\b|\.jit\b")

__all__ = ["check"]


# -- function index + call graph -------------------------------------------


class _FuncInfo:
    __slots__ = ("key", "mod", "node", "cls")

    def __init__(self, key, mod, node, cls):
        self.key = key  # (relpath, qualname)
        self.mod = mod
        self.node = node
        self.cls = cls  # enclosing class name or None


def _index_module(mod: Module):
    """(functions, import_aliases, from_imports) for one module.

    functions: {qualname: _FuncInfo} where a nested def's qualname is
    ``outer.inner`` — calls inside nested defs are attributed to the
    OUTERMOST enclosing def so reachability flows through closures the
    way execution does (a hot function's local helper is hot).
    """
    funcs: dict = {}
    aliases: dict = {}  # local alias -> dotted module
    from_imports: dict = {}  # local name -> (module, attr)

    def walk(node, prefix, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                q = f"{prefix}.{child.name}" if prefix else child.name
                funcs[q] = _FuncInfo((mod.relpath, q), mod, child, cls)
                walk(child, q, cls)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                walk(child, q, child.name)
            elif isinstance(child, ast.Import):
                for a in child.names:
                    aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(child, ast.ImportFrom):
                if child.module and child.level == 0:
                    for a in child.names:
                        from_imports[a.asname or a.name] = (
                            child.module,
                            a.name,
                        )
            else:
                walk(child, prefix, cls)

    walk(mod.tree, "", None)
    return funcs, aliases, from_imports


def _build_graph(pkg: Package):
    """functions-by-key plus call edges {key: set(key)} — built once
    per lint run (memoized on the Package: the HS and SH analyzers both
    walk the same graph)."""
    cached = getattr(pkg, "_call_graph", None)
    if cached is not None:
        return cached
    per_mod = {m.relpath: _index_module(m) for m in pkg.modules}
    # module name -> relpath, for resolving intra-package imports
    mod_by_name = {m.name: m.relpath for m in pkg.modules}
    all_funcs: dict = {}
    for rel, (funcs, _, _) in per_mod.items():
        for q, info in funcs.items():
            all_funcs[(rel, q)] = info

    def module_funcs(relpath):
        return per_mod[relpath][0] if relpath in per_mod else {}

    edges: dict = {}
    for rel, (funcs, aliases, from_imports) in per_mod.items():
        for q, info in funcs.items():
            targets = edges.setdefault(info.key, set())
            for call in ast.walk(info.node):
                if not isinstance(call, ast.Call):
                    continue
                f = call.func
                if isinstance(f, ast.Name):
                    name = f.id
                    # same-module function (top-level name)
                    if name in funcs and "." not in name:
                        targets.add(funcs[name].key)
                    elif name in from_imports:
                        m, attr = from_imports[name]
                        trel = mod_by_name.get(m)
                        if trel and attr in module_funcs(trel):
                            targets.add((trel, attr))
                elif isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name
                ):
                    base, attr = f.value.id, f.attr
                    if base == "self" and info.cls:
                        mq = f"{info.cls}.{attr}"
                        # method of the lexically enclosing class,
                        # whatever nesting prefix it carries
                        for cq, cinfo in funcs.items():
                            if cq == mq or cq.endswith("." + mq):
                                targets.add(cinfo.key)
                    elif base in aliases:
                        trel = mod_by_name.get(aliases[base])
                        if trel and attr in module_funcs(trel):
                            targets.add((trel, attr))
                    elif base in from_imports:
                        m, a = from_imports[base]
                        trel = mod_by_name.get(f"{m}.{a}" if a else m)
                        if trel and attr in module_funcs(trel):
                            targets.add((trel, attr))
    pkg._call_graph = (all_funcs, edges)
    return all_funcs, edges


def _hot_set(pkg: Package, cfg: Config, all_funcs, edges):
    roots = []
    for spec in cfg.hot_roots:
        rel, _, q = spec.partition("::")
        if (rel, q) in all_funcs:
            roots.append((rel, q))
    seen = set(roots)
    stack = list(roots)
    while stack:
        key = stack.pop()
        for t in edges.get(key, ()):
            if t not in seen:
                seen.add(t)
                stack.append(t)
    # nested defs of a hot function are lexically inside it and already
    # scanned with it; add them so the ownership check below is exact
    hot = set(seen)
    for rel, q in seen:
        for (orel, oq), _info in all_funcs.items():
            if orel == rel and oq.startswith(q + "."):
                hot.add((orel, oq))
    return hot


# -- device-value tracking --------------------------------------------------

_DEVICE_ROOTS = {"jnp", "jax", "lax"}
# Calls that PRODUCE host values: the explicit fetch, plus numpy
# materializations (flagged as HS002 where they convert a device value,
# but their RESULT is a plain numpy array — downstream float()/int()
# over it must not cascade into more findings).
_HOST_CALLS = {
    "jax.device_get",
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
}


def _call_root(node: ast.Call) -> str | None:
    parts: list = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
        return ".".join(reversed(parts))
    return None


class _DeviceTracker:
    """Statement-ordered scan of one function: which local names
    provably hold device (jax) values right now."""

    def __init__(self):
        self.device: set = set()

    def expr_is_device(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                root = _call_root(sub)
                if root in _HOST_CALLS:
                    return False
                if root and root.split(".")[0] in _DEVICE_ROOTS:
                    return True
            elif (
                isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and sub.id in self.device
            ):
                return True
        return False

    def note_assign(self, targets, value) -> None:
        names = [
            t.id for t in targets if isinstance(t, ast.Name)
        ]
        if not names:
            return
        if self.expr_is_device(value):
            self.device.update(names)
        else:
            self.device.difference_update(names)


def _scan_hot_function(info: _FuncInfo) -> list:
    mod = info.mod
    findings: list = []
    tracker = _DeviceTracker()

    def flag(rule, node, msg):
        findings.append(
            Finding(rule, mod.relpath, node.lineno, node.col_offset, msg)
        )

    def scan_expr(node):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "item"
                and not sub.args
                and not sub.keywords
            ):
                flag(
                    "HS001",
                    sub,
                    "'.item()' in a hot-path function is a blocking "
                    "per-scalar device sync; fetch in bulk with "
                    "jax.device_get",
                )
                continue
            root = _call_root(sub)
            if root in ("np.asarray", "np.array", "numpy.asarray",
                        "numpy.array"):
                if sub.args and tracker.expr_is_device(sub.args[0]):
                    flag(
                        "HS002",
                        sub,
                        f"'{root}' over a device value in a hot-path "
                        "function is an implicit transfer; use "
                        "jax.device_get at a deliberate fetch point",
                    )
            elif root in ("float", "int", "bool"):
                if sub.args and tracker.expr_is_device(sub.args[0]):
                    flag(
                        "HS003",
                        sub,
                        f"'{root}()' over a device value in a hot-path "
                        "function is an implicit scalar sync",
                    )

    def scan_block(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not _def_has_marker(mod, stmt, SYNC_OK_RE):
                    scan_block(stmt.body)
                continue
            if isinstance(stmt, ast.Assign):
                scan_expr(stmt.value)
                tracker.note_assign(stmt.targets, stmt.value)
                continue
            if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                if stmt.value is not None:
                    scan_expr(stmt.value)
                    tracker.note_assign([stmt.target], stmt.value)
                continue
            # compound statements: scan their expressions, then recurse
            # (Expr/Return are covered by the 'value' field)
            for field in ("test", "iter", "value", "exc"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, ast.AST):
                    scan_expr(sub)
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    scan_expr(item.context_expr)
            if isinstance(stmt, ast.Match):
                scan_expr(stmt.subject)
                for case in stmt.cases:
                    scan_block(case.body)
            for block in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, block, None)
                if inner:
                    scan_block(inner)
            for handler in getattr(stmt, "handlers", ()):
                scan_block(handler.body)

    if _def_has_marker(mod, info.node, SYNC_OK_RE):
        return findings
    scan_block(info.node.body)
    return findings


# -- tracer leaks -----------------------------------------------------------


def _is_jit_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        try:
            if _JIT_RE.search(ast.unparse(dec)):
                return True
        except Exception:  # pragma: no cover
            continue
    return False


def _scan_tracer_leaks(mod: Module) -> list:
    findings: list = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _is_jit_decorated(node):
            continue
        globals_declared: set = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Global):
                globals_declared.update(sub.names)
        for sub in ast.walk(node):
            targets = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                targets = [sub.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    findings.append(
                        Finding(
                            "TL001",
                            mod.relpath,
                            t.lineno,
                            t.col_offset,
                            f"store to 'self.{t.attr}' inside "
                            f"jit-decorated '{node.name}' leaks a "
                            "traced value past its trace",
                        )
                    )
                elif isinstance(t, ast.Name) and t.id in globals_declared:
                    findings.append(
                        Finding(
                            "TL002",
                            mod.relpath,
                            t.lineno,
                            t.col_offset,
                            f"store to global '{t.id}' inside "
                            f"jit-decorated '{node.name}' leaks a "
                            "traced value past its trace",
                        )
                    )
    return findings


# -- entry ------------------------------------------------------------------


def check(
    pkg: Package,
    cfg: Config,
    host_sync: bool = True,
    tracer_leak: bool = True,
) -> list:
    findings: list = []
    if host_sync:
        all_funcs, edges = _build_graph(pkg)
        hot = _hot_set(pkg, cfg, all_funcs, edges)
        # scan only OUTERMOST hot functions: nested hot defs are scanned
        # as part of their parent (scan_block recurses), so scanning
        # them again would duplicate findings
        for key in sorted(hot):
            rel, q = key
            parent = q.rsplit(".", 1)[0] if "." in q else None
            if parent and (rel, parent) in hot:
                continue
            findings.extend(_scan_hot_function(all_funcs[key]))
    if tracer_leak:
        for mod in pkg.modules:
            findings.extend(_scan_tracer_leaks(mod))
    return findings
