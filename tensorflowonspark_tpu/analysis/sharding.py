"""SH: the sharding/layout static head — shardcheck's lint half.

The layout table (``compute/layout.py``) is only a single source of
truth while nothing constructs specs behind its back. These rules make
that structural:

- **SH001** — raw ``PartitionSpec(`` / ``NamedSharding(`` constructed
  outside the layout module. Every spec must come from the declarative
  table (``layout.param_shardings``, the role helpers) so a layout
  change is a table edit with a machine-checked blast radius. Escape
  for a deliberate exception: ``# lint: layout-ok: <why>`` on the
  construction line — the justification is mandatory (an empty one
  does not suppress).
- **SH002** — a string axis name in a ``PartitionSpec(...)`` literal
  (or in the layout module's own table entries) that the active layout
  does not declare in ``MESH_AXES``. Catches the ``P("fdsp")`` typo
  class at parse time instead of as a runtime mesh KeyError — or
  worse, a silently-replicated dim.
- **SH003** — a jit site on the hot call graph (the same
  walker/roots as the HS rules: ``build_train_step``,
  ``ContinuousBatcher._loop``) whose wrapped function takes large
  array params (by name convention: ``params``/``state``/``cache``/…)
  but passes neither ``in_shardings`` nor ``donate_argnums``. On the
  hot path, an unconstrained jit recompiles per placement drift and
  silently double-buffers donated-able state. Same escape comment.
- **SH004** — a literal ``with_sharding_constraint`` spec that cannot
  match any rule the layout table declares. Constraints are pins of
  table-declared layouts at program boundaries; a constraint the table
  cannot produce either fights the table (hidden reshard — exactly the
  all-gather class ``tools/shardcheck.py`` censuses) or is a typo.

The layout module's tables are **pure literals** precisely so this
analyzer can read them by AST without importing jax; see
``compute/layout.py``.
"""

from __future__ import annotations

import ast
import os
import re

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package

__all__ = ["check"]

LAYOUT_OK_RE = re.compile(r"#\s*lint:\s*layout-ok:\s*\S")

# Parameter names that hold large device arrays by repo convention —
# the static stand-in for "large array params" (sizes are a runtime
# property; names are what an AST can see).
_LARGE_PARAM_NAMES = {
    "params", "state", "opt_state", "cache", "caches", "weights",
    "draft_params",
}

_JIT_ROOTS = {"jax.jit", "jit", "pjit", "jax.pjit"}
_SHARDING_KWARGS = {
    "in_shardings", "donate_argnums", "donate_argnames",
}


def _attr_chain(node: ast.AST) -> str | None:
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _span_has_escape(mod: Module, start: int, end: int) -> bool:
    for line in range(start, end + 1):
        c = mod.comments.get(line)
        if c and LAYOUT_OK_RE.search(c):
            return True
    return False


def _has_escape(mod: Module, node: ast.AST) -> bool:
    """``# lint: layout-ok: <why>`` on any line of the node's span, or
    on the line directly above (the opening line of a wrapping
    multi-line expression)."""
    end = getattr(node, "end_lineno", node.lineno) or node.lineno
    return _span_has_escape(mod, max(1, node.lineno - 1), end)


# ---------------------------------------------------------------------------
# the declared layout, read from the layout module WITHOUT importing it
# ---------------------------------------------------------------------------


class DeclaredLayout:
    """Axis names + normalized spec tuples parsed from the layout
    module's literal tables."""

    def __init__(self, axes: set, specs: set, parsed: bool):
        self.axes = axes
        self.specs = specs  # set of normalized spec tuples
        self.parsed = parsed

    @staticmethod
    def _normalize(spec: tuple) -> tuple:
        out = [
            tuple(e) if isinstance(e, (tuple, list)) else e for e in spec
        ]
        while out and out[-1] is None:
            out.pop()
        return tuple(out)

    def declares_spec(self, spec: tuple) -> bool:
        """True when ``spec`` matches a declared rule, allowing axes the
        caller dropped to None (a constraint may pin a WEAKER layout
        than the table's rule, never a different one)."""
        norm = self._normalize(spec)
        if norm in self.specs:
            return True
        for decl in self.specs:
            if len(norm) > len(decl):
                continue
            padded = decl + (None,) * (len(norm) - len(decl))
            if all(
                e is None or e == padded[d] for d, e in enumerate(norm)
            ):
                return True
        return False


def _spec_entries(node: ast.AST):
    """Literal spec entries of one table 'spec' value / activation spec
    tuple: axis-name strings, None, nested tuples. Returns None when
    the literal shape is unexpected (computed specs are not checked)."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            sub = _spec_entries(el)
            if sub is None:
                return None
            out.append(sub if not isinstance(el, ast.Constant) else sub[0])
        return tuple(out)
    if isinstance(node, ast.Constant):
        if node.value is None or isinstance(node.value, (str, int)):
            return (node.value,)
        return None
    return None


def load_declared_layout(pkg: Package, cfg: Config) -> DeclaredLayout:
    mod = pkg.by_relpath.get(cfg.layout_module)
    tree = mod.tree if mod is not None else None
    if tree is None:
        path = os.path.join(pkg.root, cfg.layout_module)
        try:
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            return DeclaredLayout(set(), set(), parsed=False)

    axes: set = set()
    specs: set = {()}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        name = target.id
        try:
            value = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            continue
        if name == "MESH_AXES":
            axes.update(value)
        elif name == "BATCH_AXES":
            specs.add((tuple(value),))
        elif name == "LAYOUT_TABLES":
            for rules in value.values():
                for rule in rules:
                    spec = tuple(
                        tuple(e) if isinstance(e, list) else e
                        for e in rule.get("spec", ())
                    )
                    specs.add(DeclaredLayout._normalize(spec))
        elif name in (
            "ACTIVATION_SPECS", "DECODE_CACHE_SPECS", "SERVE_CACHE_SPECS"
        ):
            for spec in value.values():
                spec = tuple(
                    tuple(e) if isinstance(e, list) else e for e in spec
                )
                specs.add(DeclaredLayout._normalize(spec))
    return DeclaredLayout(axes, specs, parsed=bool(axes))


# ---------------------------------------------------------------------------
# per-module constructor binding resolution
# ---------------------------------------------------------------------------


class _Bindings:
    """Local names under which PartitionSpec/NamedSharding are
    reachable in one module."""

    def __init__(self, mod: Module):
        self.ctor_names: dict = {}  # local name -> 'PartitionSpec'|'NamedSharding'
        self.sharding_mods: set = set()  # aliases of jax.sharding
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module in (
                "jax.sharding", "jax.interpreters.pxla"
            ):
                for a in node.names:
                    if a.name in ("PartitionSpec", "NamedSharding"):
                        self.ctor_names[a.asname or a.name] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name == "sharding":
                        self.sharding_mods.add(a.asname or "sharding")
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.sharding":
                        self.sharding_mods.add(a.asname or "jax.sharding")
                    elif a.name == "jax":
                        self.sharding_mods.add(
                            (a.asname or "jax") + ".sharding"
                        )

    def ctor_of(self, call: ast.Call) -> str | None:
        """'PartitionSpec' / 'NamedSharding' when this call constructs
        one, else None."""
        f = call.func
        if isinstance(f, ast.Name):
            return self.ctor_names.get(f.id)
        chain = _attr_chain(f)
        if not chain:
            return None
        base, _, leaf = chain.rpartition(".")
        if leaf in ("PartitionSpec", "NamedSharding") and (
            base in self.sharding_mods or base == "jax.sharding"
        ):
            return leaf
        return None


# ---------------------------------------------------------------------------
# SH001 / SH002 / SH004
# ---------------------------------------------------------------------------


def _literal_axis_names(call: ast.Call):
    """(node, axis-name) for every string literal in a PartitionSpec
    call's args — including inside tuple args (multi-axis dims)."""
    for arg in call.args:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                yield sub, sub.value


def _literal_spec(call: ast.Call) -> tuple | None:
    """The spec tuple of an all-literal PartitionSpec call, else None."""
    out = []
    for arg in call.args:
        got = _spec_entries(arg)
        if got is None:
            return None
        if isinstance(arg, (ast.Tuple, ast.List)):
            out.append(got)
        else:
            out.append(got[0])
    return tuple(out)


def _scan_constructors(
    mod: Module, cfg: Config, declared: DeclaredLayout, findings: list
) -> None:
    is_layout = mod.relpath == cfg.layout_module
    b = _Bindings(mod)
    constraint_spec_nodes: set = set()

    # collect P-literals that sit inside with_sharding_constraint calls
    # first, so SH004 fires on them (SH002 still applies to their axis
    # names; SH001 does too when outside the layout module)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func) or (
            node.func.id if isinstance(node.func, ast.Name) else ""
        )
        if chain and chain.rpartition(".")[2] == "with_sharding_constraint":
            for arg in node.args[1:] + [k.value for k in node.keywords]:
                for sub in ast.walk(arg):
                    if (
                        isinstance(sub, ast.Call)
                        and b.ctor_of(sub) == "PartitionSpec"
                    ):
                        constraint_spec_nodes.add(sub)

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        ctor = b.ctor_of(node)
        if ctor is None:
            continue
        if not is_layout and not _has_escape(mod, node):
            findings.append(
                Finding(
                    "SH001",
                    mod.relpath,
                    node.lineno,
                    node.col_offset,
                    f"raw {ctor}(...) constructed outside the layout "
                    f"table ({cfg.layout_module}); consume "
                    "compute.layout helpers/tables instead, or escape "
                    "with '# lint: layout-ok: <why>'",
                )
            )
        if ctor == "PartitionSpec" and declared.parsed:
            for sub, axis in _literal_axis_names(node):
                if axis not in declared.axes:
                    findings.append(
                        Finding(
                            "SH002",
                            mod.relpath,
                            sub.lineno,
                            sub.col_offset,
                            f"spec axis {axis!r} is not declared by the "
                            "active layout (MESH_AXES: "
                            f"{sorted(declared.axes)})",
                        )
                    )
            if node in constraint_spec_nodes:
                spec = _literal_spec(node)
                if spec is not None and not declared.declares_spec(spec):
                    findings.append(
                        Finding(
                            "SH004",
                            mod.relpath,
                            node.lineno,
                            node.col_offset,
                            f"with_sharding_constraint spec {spec!r} "
                            "matches no rule in the layout table — it "
                            "either fights the table (hidden reshard) "
                            "or is a typo; declare it or use a layout "
                            "helper",
                        )
                    )


def _scan_layout_tables(
    mod: Module, declared: DeclaredLayout, findings: list
) -> None:
    """SH002 inside the layout module itself: every axis string in a
    table 'spec' entry (or activation/cache spec) must be declared."""

    def check_spec_node(node: ast.AST) -> None:
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Constant)
                and isinstance(sub.value, str)
                and sub.value not in declared.axes
            ):
                findings.append(
                    Finding(
                        "SH002",
                        mod.relpath,
                        sub.lineno,
                        sub.col_offset,
                        f"layout table declares spec axis {sub.value!r} "
                        "which MESH_AXES does not declare",
                    )
                )

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if target.id == "LAYOUT_TABLES":
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Dict):
                    for k, v in zip(sub.keys, sub.values):
                        if (
                            isinstance(k, ast.Constant)
                            and k.value == "spec"
                        ):
                            check_spec_node(v)
        elif target.id in (
            "ACTIVATION_SPECS",
            "DECODE_CACHE_SPECS",
            "SERVE_CACHE_SPECS",
            "BATCH_AXES",
        ):
            if isinstance(node.value, ast.Dict):
                # keys are role names, not axes — check values only
                for v in node.value.values:
                    check_spec_node(v)
            else:
                check_spec_node(node.value)


# ---------------------------------------------------------------------------
# SH003 — unconstrained hot-path jit of large-array params
# ---------------------------------------------------------------------------


def _jit_kwargs(call: ast.Call) -> set:
    return {k.arg for k in call.keywords if k.arg}


def _fn_param_names(fn) -> set:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    return set(names)


def _scan_hot_jits(pkg: Package, cfg: Config, findings: list) -> None:
    from tensorflowonspark_tpu.analysis.hostsync import (
        _build_graph,
        _hot_set,
        _index_module,
    )

    all_funcs, edges = _build_graph(pkg)
    hot = _hot_set(pkg, cfg, all_funcs, edges)
    if not hot:
        return
    # same-module top-level function defs, for resolving jit(fn) args
    mod_defs = {
        m.relpath: _index_module(m)[0] for m in pkg.modules
    }

    def flag(mod: Module, node: ast.AST, what: str) -> None:
        findings.append(
            Finding(
                "SH003",
                mod.relpath,
                node.lineno,
                node.col_offset,
                f"hot-path jit of {what} passes neither in_shardings "
                "nor donate_argnums: placement drifts silently and "
                "state double-buffers; take shardings from the layout "
                "table (or '# lint: layout-ok: <why>')",
            )
        )

    seen: set = set()
    for key in sorted(hot):
        info = all_funcs[key]
        mod = info.mod
        for node in ast.walk(info.node):
            # decorated defs: @jax.jit / @partial(jax.jit, ...)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dec_call = dec if isinstance(dec, ast.Call) else None
                    root = dec_call.func if dec_call else dec
                    chain = _attr_chain(root) or (
                        root.id if isinstance(root, ast.Name) else ""
                    )
                    kwargs: set = set()
                    if chain == "partial" or chain == "functools.partial":
                        if dec_call and dec_call.args:
                            inner = dec_call.args[0]
                            chain = _attr_chain(inner) or (
                                inner.id
                                if isinstance(inner, ast.Name)
                                else ""
                            )
                            kwargs = _jit_kwargs(dec_call)
                    elif dec_call is not None:
                        kwargs = _jit_kwargs(dec_call)
                    if chain not in _JIT_ROOTS:
                        continue
                    mark = (mod.relpath, node.lineno, node.col_offset)
                    if mark in seen:
                        continue
                    seen.add(mark)
                    if kwargs & _SHARDING_KWARGS:
                        continue
                    large = _fn_param_names(node) & _LARGE_PARAM_NAMES
                    if not large:
                        continue
                    # escape scope: decorator line through the def's
                    # first body line — NOT the whole function body
                    if _span_has_escape(
                        mod,
                        dec.lineno,
                        node.body[0].lineno if node.body else node.lineno,
                    ):
                        continue
                    flag(mod, dec, f"'{node.name}({', '.join(sorted(large))})'")
                continue
            # call form: jax.jit(fn, ...)
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func) or (
                node.func.id if isinstance(node.func, ast.Name) else ""
            )
            if chain not in _JIT_ROOTS or not node.args:
                continue
            mark = (mod.relpath, node.lineno, node.col_offset)
            if mark in seen:
                continue
            seen.add(mark)
            if _jit_kwargs(node) & _SHARDING_KWARGS:
                continue
            target = node.args[0]
            large: set = set()
            name = None
            if isinstance(target, ast.Lambda):
                large = _fn_param_names(target) & _LARGE_PARAM_NAMES
                name = "<lambda>"
            elif isinstance(target, ast.Name):
                fn_info = mod_defs.get(mod.relpath, {}).get(target.id)
                if fn_info is None:
                    # maybe nested within the hot function itself
                    for sub in ast.walk(info.node):
                        if (
                            isinstance(
                                sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                            )
                            and sub.name == target.id
                        ):
                            fn_info = type(
                                "X", (), {"node": sub}
                            )  # lightweight holder
                            break
                if fn_info is not None:
                    large = (
                        _fn_param_names(fn_info.node) & _LARGE_PARAM_NAMES
                    )
                    name = target.id
            if not large or _has_escape(mod, node):
                continue
            flag(mod, node, f"'{name}({', '.join(sorted(large))})'")


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def check(pkg: Package, cfg: Config) -> list:
    findings: list = []
    declared = load_declared_layout(pkg, cfg)
    for mod in pkg.modules:
        _scan_constructors(mod, cfg, declared, findings)
        if mod.relpath == cfg.layout_module and declared.parsed:
            _scan_layout_tables(mod, declared, findings)
    _scan_hot_jits(pkg, cfg, findings)
    return findings
