"""OB001: obs metric names must be literal, snake_case, unit-suffixed.

The observability plane is only queryable if metric names are static
and consistent: a dashboard, the driver aggregator's merge, and the
future autotuner all key on exact names. Three failure modes this rule
blocks at build time:

- **Dynamic names** (f-strings, variables): un-greppable, and the
  cardinality is unbounded — a per-request name leaks series forever.
  (Dynamic DIMENSIONS belong in labels, which are per-observation.)
- **Case/format drift** (``CamelCase``, dots): Prometheus convention
  is snake_case; ``sanitize_name`` exists for *mirrored* foreign names,
  not hand-registered ones.
- **Missing unit suffixes**: ``engine_ttft`` alone is ambiguous
  (seconds? ms?); promtool's convention is the suffix IS the unit —
  counters end ``_total``, histograms end in their unit
  (``_seconds`` / ``_bytes``). Gauges are often dimensionless (queue
  depth, slots busy) so only literalness + snake_case is enforced.

Scope: calls to ``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)``
in modules that import :mod:`tensorflowonspark_tpu.obs` (or its
``registry``) — the only modules where those method names mean the obs
registry. ``# lint: metric-name-ok`` on the call line suppresses (the
one legitimate dynamic name: ``MetricsWriter``'s mirror of arbitrary
scalar names, which sanitizes instead).
"""

from __future__ import annotations

import ast
import re

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package

__all__ = ["check"]

_OBS_MODULES = (
    "tensorflowonspark_tpu.obs",
    "tensorflowonspark_tpu.obs.registry",
)
_METHODS = {"counter", "gauge", "histogram"}
_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_SUFFIXES = {
    "counter": ("_total",),
    "histogram": ("_seconds", "_bytes"),
}
_SUPPRESS = "lint: metric-name-ok"


def _imports_obs(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.startswith(_OBS_MODULES[0]) for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            mod = node.module or ""
            if mod.startswith(_OBS_MODULES[0]):
                return True
            if mod == "tensorflowonspark_tpu" and any(
                a.name == "obs" for a in node.names
            ):
                return True
    return False


class _Checker(ast.NodeVisitor):
    def __init__(self, mod: Module):
        self.mod = mod
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(
                "OB001", self.mod.relpath, node.lineno, node.col_offset, msg
            )
        )

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _METHODS
            and _SUPPRESS not in self.mod.comments.get(node.lineno, "")
        ):
            kind = func.attr
            arg = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "name":
                    arg = kw.value
            if arg is None:
                pass  # not a registration call shape; leave it alone
            elif not (
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            ):
                self._flag(
                    node,
                    f"obs {kind} name must be a string literal (dynamic "
                    "names are un-greppable and risk unbounded series "
                    "cardinality; put dynamic dimensions in labels)",
                )
            else:
                name = arg.value
                if not _SNAKE.match(name):
                    self._flag(
                        node,
                        f"obs metric name {name!r} must be snake_case "
                        "([a-z][a-z0-9_]*)",
                    )
                elif kind in _SUFFIXES and not name.endswith(
                    _SUFFIXES[kind]
                ):
                    want = "/".join(_SUFFIXES[kind])
                    self._flag(
                        node,
                        f"obs {kind} name {name!r} must end with its "
                        f"unit suffix ({want})",
                    )
        self.generic_visit(node)


def check(pkg: Package, cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for mod in pkg.modules:
        if not _imports_obs(mod.tree):
            continue
        checker = _Checker(mod)
        checker.visit(mod.tree)
        findings.extend(checker.findings)
    return findings
