"""AT001: tunable knobs mutate only through their sanctioned paths.

The autotune controller (``autotune/registry.py``) is only trustworthy
if it is the ONLY writer of the knobs it tunes: an ad-hoc
``engine._decode_block = 8`` anywhere else silently invalidates every
baseline/revert decision the controller makes (it would revert to a
value nobody set, or judge a regression caused by the stranger's
write). So the registry module declares, as plain literals:

- :data:`TUNABLE_ATTRS` — the protected attribute names; and
- :data:`SANCTIONED` — the ``ClassName.method`` qualified names allowed
  to assign them (each knob's constructor default plus its declared
  live-actuation method).

This rule (the FP001 pattern: both literals are parsed standalone from
``cfg.autotune_module`` on disk, no import) flags every other
assignment — plain, augmented, or annotated — to a protected attribute
anywhere in the linted package. A justified exception carries
``# lint: knob-ok: <why>`` on the assignment's line; the justification
text is required, exactly like ``lockfree-read``.
"""

from __future__ import annotations

import ast
import os
import re

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package

KNOB_OK_RE = re.compile(r"#\s*lint:\s*knob-ok\b:?\s*(.*)")

__all__ = ["check", "KNOB_OK_RE"]


def _registry_literals(root: str, cfg: Config) -> tuple:
    """``(TUNABLE_ATTRS, SANCTIONED)`` string sets parsed from the
    registry module on disk, or ``(None, None)`` when it cannot be
    read — the rule then no-ops (a repo without the autotune plane has
    nothing to protect)."""
    path = os.path.join(root, cfg.autotune_module)
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None, None
    out = {"TUNABLE_ATTRS": None, "SANCTIONED": None}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id in out:
                out[t.id] = {
                    n.value
                    for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                }
    return out["TUNABLE_ATTRS"], out["SANCTIONED"]


class _Checker(ast.NodeVisitor):
    """Flags assignments to protected attributes outside sanctioned
    ``ClassName.method`` scopes. The scope stack tracks (class,
    function) nesting; a nested helper/lambda inside a sanctioned
    method inherits its sanction (the method owns that code)."""

    def __init__(self, mod: Module, attrs: set, sanctioned: set):
        self.mod = mod
        self.attrs = attrs
        self.sanctioned = sanctioned
        self._stack: list = []  # ("class"|"fn", name)
        self.findings: list = []

    # -- scope tracking -------------------------------------------------

    def visit_ClassDef(self, node):
        self._stack.append(("class", node.name))
        self.generic_visit(node)
        self._stack.pop()

    def _visit_fn(self, node):
        self._stack.append(("fn", node.name))
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _in_sanctioned_scope(self) -> bool:
        for i in range(len(self._stack) - 1):
            kind, name = self._stack[i]
            nkind, nname = self._stack[i + 1]
            if kind == "class" and nkind == "fn":
                if f"{name}.{nname}" in self.sanctioned:
                    return True
        return False

    # -- assignment forms -----------------------------------------------

    def _check_target(self, stmt: ast.stmt, target: ast.AST) -> None:
        for node in ast.walk(target):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in self.attrs
            ):
                self._flag_unless_ok(stmt, node.attr)
                return

    def _flag_unless_ok(self, stmt: ast.stmt, attr: str) -> None:
        if self._in_sanctioned_scope():
            return
        end = getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
        for line in range(stmt.lineno, end + 1):
            c = self.mod.comments.get(line)
            m = KNOB_OK_RE.search(c) if c else None
            if m is not None:
                if m.group(1).strip():
                    return  # justified: suppressed, reviewed in place
                self.findings.append(
                    Finding(
                        "AT001",
                        self.mod.relpath,
                        stmt.lineno,
                        stmt.col_offset,
                        "'# lint: knob-ok:' requires a justification "
                        "(why is this write outside the registry safe "
                        "for the controller?)",
                    )
                )
                return
        self.findings.append(
            Finding(
                "AT001",
                self.mod.relpath,
                stmt.lineno,
                stmt.col_offset,
                f"tunable attribute '{attr}' assigned outside its "
                "sanctioned actuation path (autotune/registry.py "
                "SANCTIONED) — an untracked write invalidates the "
                "controller's baseline/revert bookkeeping; route it "
                "through KnobRegistry.set or justify with "
                "'# lint: knob-ok: <why>'",
            )
        )

    def visit_Assign(self, node):
        for t in node.targets:
            self._check_target(node, t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_target(node, node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._check_target(node, node.target)
        self.generic_visit(node)


def check(pkg: Package, cfg: Config) -> list:
    attrs, sanctioned = _registry_literals(pkg.root, cfg)
    if not attrs:
        return []
    sanctioned = sanctioned or set()
    findings: list = []
    for mod in pkg.modules:
        checker = _Checker(mod, attrs, sanctioned)
        checker.visit(mod.tree)
        findings.extend(checker.findings)
    return findings
