"""shardcheck trace head: collective census of a jitted program.

The layout table (``compute/layout.py``) declares what the sharding
SHOULD be; the SH lint rules prove the code consumes the table. This
module proves what the table actually BUYS: it lowers a program
abstractly (no parameter memory is ever allocated — ``ShapeDtypeStruct``
leaves all the way down) and counts the collective/reshard operations
in two places:

- **jaxpr head** (:func:`jaxpr_census`) — explicit collectives the
  program itself contains (``psum``/``all_gather``/``all_to_all``/… from
  shard_map'd kernels: ring attention, Ulysses, MoE dispatch, the BN
  cross-shard stats). Each count carries *parameter provenance*: a
  forward dataflow walk maps every collective's operands back to the
  top-level inputs that feed them, so a census line reads
  ``psum[params/layer0/attn/q_proj/kernel]``, not just ``psum: 3``.
- **HLO head** (:func:`hlo_census`) — collectives *XLA's SPMD
  partitioner inserts* to satisfy the shardings (the GSPMD pass runs at
  compile time, so jaxprs never show these). This is where a layout
  edit's hidden all-gather lives: drop the fsdp axis from one param
  rule and the weight suddenly all-gathers every step — invisible in
  the jaxpr, a count diff here.

``tools/shardcheck.py`` drives this against the real train step
(:func:`compute.train.make_step_fn`) on faux CPU devices and gates the
result against a committed per-model baseline
(``tools/shardcheck_baseline.json``): an unintended collective becomes
a tier-1 diff, not a silent MFU regression.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Mapping

__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "HLO_COLLECTIVES",
    "census",
    "diff_census",
    "hlo_census",
    "jaxpr_census",
]

# jaxpr-level collective/reshard primitives worth counting. axis_index
# and friends are cheap/local; these move data across devices.
COLLECTIVE_PRIMITIVES = frozenset(
    {
        "all_gather",
        "all_gather_invariant",
        "all_to_all",
        "pbroadcast",
        "pgather",
        "ppermute",
        "psum",
        "psum2",  # shard_map's rewritten psum on jax 0.4.x
        "psum_invariant",
        "psum_scatter",
        "reduce_scatter",
    }
)

# post-SPMD HLO collective opcodes (async '-start' forms count once;
# their '-done' halves do not).
HLO_COLLECTIVES = (
    "all-gather",
    "all-reduce",
    "all-to-all",
    "collective-broadcast",
    "collective-permute",
    "reduce-scatter",
)

_HLO_RE = re.compile(
    r"=\s+(?:\([^)]*\)|(?P<shape>[a-z0-9]+\[[0-9,]*\]))\S*\s+"
    r"(?P<op>" + "|".join(HLO_COLLECTIVES) + r")(?:-start)?\("
)

_MAX_PROVENANCE_LABELS = 3


def _leaf_labels(args: tuple, arg_names: tuple | None = None) -> list:
    """Flattened '/'-joined path label per leaf of ``args``, prefixed
    by the argument's name (matching the order jax flattens tracing
    inputs: per-arg pytree order)."""
    import jax

    from tensorflowonspark_tpu.compute.layout import _path_name

    labels: list = []
    for i, arg in enumerate(args):
        prefix = (
            arg_names[i]
            if arg_names and i < len(arg_names)
            else f"arg{i}"
        )
        leaves, _ = jax.tree_util.tree_flatten_with_path(arg)
        for path, _leaf in leaves:
            name = _path_name(path)
            labels.append(f"{prefix}/{name}" if name else prefix)
    return labels


def _provenance_key(prim: str, labels: frozenset) -> str:
    if not labels:
        return prim
    ordered = sorted(labels)
    if len(ordered) > _MAX_PROVENANCE_LABELS:
        ordered = ordered[:_MAX_PROVENANCE_LABELS] + [
            f"+{len(labels) - _MAX_PROVENANCE_LABELS}"
        ]
    return f"{prim}[{';'.join(ordered)}]"


def _sub_jaxprs(params: Mapping[str, Any]):
    """Every (Closed)Jaxpr hiding in an eqn's params (pjit 'jaxpr',
    scan/while bodies, cond 'branches', remat, custom_vjp, …)."""
    for value in params.values():
        stack = [value]
        while stack:
            v = stack.pop()
            if isinstance(v, (tuple, list)):
                stack.extend(v)
            elif hasattr(v, "jaxpr") and hasattr(v, "consts"):
                yield v.jaxpr  # ClosedJaxpr
            elif hasattr(v, "eqns") and hasattr(v, "invars"):
                yield v  # raw Jaxpr


def _walk_jaxpr(jaxpr, env: dict, counts: Counter) -> None:
    """Forward dataflow over one jaxpr: ``env`` maps vars to frozensets
    of root labels; collectives record (primitive, provenance)."""

    def read(v) -> frozenset:
        if hasattr(v, "val"):  # Literal
            return frozenset()
        return env.get(v, frozenset())

    for eqn in jaxpr.eqns:
        in_labels = frozenset()
        for v in eqn.invars:
            in_labels |= read(v)
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMITIVES:
            counts[_provenance_key(prim, in_labels)] += 1
        for sub in _sub_jaxprs(eqn.params):
            sub_env: dict = {}
            # positional best-effort: pjit/call line up 1:1; scan/while
            # prepend consts — close enough for provenance, and the
            # fallback (empty label set) is safe
            for outer, inner in zip(eqn.invars, sub.invars):
                sub_env[inner] = read(outer)
            _walk_jaxpr(sub, sub_env, counts)
        for v in eqn.outvars:
            env[v] = in_labels


def jaxpr_census(fn, args: tuple, arg_names: tuple | None = None) -> dict:
    """{'<prim>[<roots>]': count} for explicit collectives in ``fn``
    traced at ``args`` (arrays or ShapeDtypeStructs)."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    labels = _leaf_labels(args, arg_names)
    jaxpr = closed.jaxpr
    env = {
        var: frozenset({label})
        for var, label in zip(jaxpr.invars, labels)
    }
    counts: Counter = Counter()
    _walk_jaxpr(jaxpr, env, counts)
    return dict(sorted(counts.items()))


def hlo_census(
    fn,
    args: tuple,
    in_shardings: Any = None,
    out_shardings: Any = None,
    donate_argnums: tuple = (),
) -> dict:
    """{'<op> <shape>': count} of collectives in the SPMD-partitioned,
    compiled HLO — the GSPMD-inserted traffic the jaxpr cannot show.
    AOT: no buffers are allocated, only compiled."""
    import jax

    kwargs: dict = {"donate_argnums": donate_argnums}
    if in_shardings is not None:
        kwargs["in_shardings"] = in_shardings
    if out_shardings is not None:
        kwargs["out_shardings"] = out_shardings
    lowered = jax.jit(fn, **kwargs).lower(*args)
    text = lowered.compile().as_text()
    counts: Counter = Counter()
    for m in _HLO_RE.finditer(text):
        shape = m.group("shape") or "tuple"
        counts[f"{m.group('op')} {shape}"] += 1
    return dict(sorted(counts.items()))


def census(
    fn,
    args: tuple,
    in_shardings: Any = None,
    out_shardings: Any = None,
    donate_argnums: tuple = (),
    meta: Mapping[str, Any] | None = None,
    arg_names: tuple | None = None,
) -> dict:
    """Both heads plus metadata. ``meta`` records HOW the census was
    taken (model, mesh, shapes, jax version); the gate compares only
    the census dicts, so environment drift is visible but not load-
    bearing."""
    import jax

    full_meta = {"jax_version": jax.__version__}
    full_meta.update(meta or {})
    return {
        "meta": full_meta,
        "jaxpr": jaxpr_census(fn, args, arg_names),
        "hlo": hlo_census(
            fn,
            args,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=donate_argnums,
        ),
    }


def diff_census(baseline: Mapping[str, Any], current: Mapping[str, Any]):
    """Human-readable diff lines between two census dicts ('' == equal).
    Compares the 'jaxpr' and 'hlo' heads only — meta is informational."""
    lines: list = []
    for head in ("jaxpr", "hlo"):
        base = dict(baseline.get(head, {}))
        cur = dict(current.get(head, {}))
        for key in sorted(set(base) | set(cur)):
            b, c = base.get(key, 0), cur.get(key, 0)
            if b != c:
                lines.append(f"{head}: {key}: baseline {b} != current {c}")
    return lines
