"""BL001: provably-blocking calls under a lock or a live frame view.

The other half of the tfsan static head (see :mod:`.lockorder`). The
shm-ring feed plane's deadlock class (docs/DESIGN.md §2, liveness rules
1–2) has one mechanical shape: an *unbounded* wait executed while this
thread pins a resource another thread needs to make progress — a held
lock, or a refcounted columnar frame view whose ring slot the producer
is waiting to reuse. This rule mechanizes that review checklist.

A call is *provably blocking* when it has no way to time out:

- ``<queueish>.get(...)`` with no ``timeout`` (base name mentions a
  queue role: ``queue``/``_q``/``q``; ``dict.get(k)`` never matches);
- zero-argument ``.join()`` with no ``timeout`` (thread/process/queue
  join — ``str.join`` always takes an argument);
- ``.recv(...)`` / ``.recv_bytes(...)`` (sockets, multiprocessing
  ``Connection`` — no timeout parameter exists);
- ``.pop_frame(...)`` with no ``timeout`` (``ShmRing`` consumer pop);
- ``.accept()`` (listening sockets).

Flagged when such a call executes:

1. **while a lock is lexically held** (``with <lock>:`` in scope) —
   directly, or through the package call graph (a function that blocks,
   called from under a lock, blocks under that lock);
2. **while a columnar frame view is live in scope** — a local assigned
   from ``pop_frame``/``decode_frame`` that has not been reassigned,
   ``del``-ed or cleared to ``None`` before the blocking call. A live
   view pins its ring slot; blocking for frame N+1 while pinning frame N
   deadlocks the plane once frames approach the ring capacity.

``# lint: blocking-ok`` on the call's line (or the enclosing ``def``
line) suppresses the rule — for sites whose boundedness lives elsewhere
(a peer guaranteed to close the socket, a drained queue).
"""

from __future__ import annotations

import ast
import re

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package
from tensorflowonspark_tpu.analysis.locks import _def_has_marker
from tensorflowonspark_tpu.analysis.lockorder import (
    _transitive_acquires,
    lock_key,
    scan_functions,
)

BLOCKING_OK_RE = re.compile(r"#\s*lint:\s*blocking-ok\b")
_QUEUEISH_RE = re.compile(r"(?:^|_)q(?:ueue)?s?(?:_in|_out)?$|queue")
_VIEW_CALLS = ("pop_frame", "decode_frame")

__all__ = ["check"]


def _has_timeout(call: ast.Call) -> bool:
    return any(k.arg == "timeout" for k in call.keywords)


def _base_name(expr: ast.AST) -> str:
    """Final name component of the receiver expression."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def blocking_reason(call: ast.Call) -> str | None:
    """Why this call provably blocks, or None."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    name = f.attr
    if name == "get":
        if (
            not _has_timeout(call)
            and not call.args
            and not call.keywords
            and _QUEUEISH_RE.search(_base_name(f.value))
        ):
            return "queue get() without timeout"
        if (
            not _has_timeout(call)
            and call.args
            and all(
                isinstance(a, ast.Constant) and a.value is True
                for a in call.args[:1]
            )
            and len(call.args) == 1
            and _QUEUEISH_RE.search(_base_name(f.value))
        ):
            return "queue get(block=True) without timeout"
        return None
    if name == "join":
        if not call.args and not _has_timeout(call):
            return "join() without timeout"
        return None
    if name in ("recv", "recv_bytes"):
        return f"{name}() (no timeout exists)"
    if name == "pop_frame":
        if not _has_timeout(call):
            return "ShmRing.pop_frame() without timeout"
        return None
    if name == "accept" and not call.args:
        return "socket accept()"
    return None


class _BlockScan(ast.NodeVisitor):
    """Statement-ordered scan of one function: blocking calls, the lock
    stack, and live frame-view locals at each call site."""

    def __init__(self, mod: Module, cls: str | None):
        self.mod = mod
        self.cls = cls
        # (node, reason, tuple(held), tuple(live_views))
        self.blocking: list = []
        self._held: list = []
        self._views: dict = {}  # name -> assignment line

    # nested defs are separate functions (see lockorder._FnScan)
    def _skip(self, node):
        pass

    visit_FunctionDef = _skip
    visit_AsyncFunctionDef = _skip
    visit_Lambda = _skip

    def _exempt(self, node) -> bool:
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        for line in range(node.lineno, end + 1):
            c = self.mod.comments.get(line)
            if c and BLOCKING_OK_RE.search(c):
                return True
        return False

    def visit_With(self, node):
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            key = lock_key(self.mod, self.cls, item.context_expr)
            if key is not None:
                self._held.append(key)
                pushed += 1
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        if pushed:
            del self._held[-pushed:]

    visit_AsyncWith = visit_With

    def _note_views(self, targets, value) -> None:
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names:
            return
        is_view = False
        if isinstance(value, ast.Call):
            f = value.func
            fname = (
                f.attr
                if isinstance(f, ast.Attribute)
                else (f.id if isinstance(f, ast.Name) else "")
            )
            is_view = fname in _VIEW_CALLS
        for n in names:
            if is_view:
                self._views[n] = value.lineno
            else:
                self._views.pop(n, None)

    def visit_Assign(self, node):
        self.visit(node.value)
        self._note_views(node.targets, node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
            self._note_views([node.target], node.value)

    def visit_Delete(self, node):
        for t in node.targets:
            if isinstance(t, ast.Name):
                self._views.pop(t.id, None)

    def visit_Call(self, node):
        reason = blocking_reason(node)
        if reason is not None and not self._exempt(node):
            self.blocking.append(
                (
                    node,
                    reason,
                    tuple(self._held),
                    tuple(sorted(self._views)),
                )
            )
        self.generic_visit(node)


def _transitive_blockers(call_edges: dict, direct: dict) -> dict:
    """{func_key: (reason, relpath, line) | None} — the first blocking
    call reachable from each function (its own, or a callee's)."""
    out = dict(direct)
    changed = True
    while changed:
        changed = False
        for key, targets in call_edges.items():
            if out.get(key) is not None:
                continue
            for t in sorted(targets):
                found = out.get(t)
                if found is not None:
                    out[key] = found
                    changed = True
                    break
    return out


def check(pkg: Package, cfg: Config, shared=None) -> list:
    all_funcs, call_edges, lock_scans = shared or scan_functions(pkg)
    findings: list = []
    direct: dict = {}  # func_key -> (reason, relpath, line) | None
    scans: dict = {}

    for key, info in all_funcs.items():
        if _def_has_marker(info.mod, info.node, BLOCKING_OK_RE):
            direct[key] = None
            scans[key] = None
            continue
        scan = _BlockScan(info.mod, info.cls)
        for stmt in info.node.body:
            scan.visit(stmt)
        scans[key] = scan
        direct[key] = None
        for node, reason, _held, _views in scan.blocking:
            direct[key] = (reason, info.mod.relpath, node.lineno)
            break

    def short(lock):
        return lock.split("::", 1)[1] if "::" in lock else lock

    # direct findings: blocking under a lexically-held lock / live view
    for key, scan in scans.items():
        if scan is None:
            continue
        for node, reason, held, views in scan.blocking:
            if held:
                findings.append(
                    Finding(
                        "BL001",
                        scan.mod.relpath,
                        node.lineno,
                        node.col_offset,
                        f"provably-blocking call ({reason}) while "
                        f"holding {', '.join(short(h) for h in held)} — "
                        "an unbounded wait under a lock wedges every "
                        "contender (DESIGN.md liveness rules)",
                    )
                )
            elif views:
                findings.append(
                    Finding(
                        "BL001",
                        scan.mod.relpath,
                        node.lineno,
                        node.col_offset,
                        f"provably-blocking call ({reason}) while frame "
                        f"view(s) {', '.join(views)} are live in scope — "
                        "a pinned ring slot starves the producer; clear "
                        "the view before blocking (DESIGN.md liveness "
                        "rule 2)",
                    )
                )

    # call-graph findings: calling a (transitively) blocking function
    # while lexically holding a lock
    blockers = _transitive_blockers(call_edges, direct)
    from tensorflowonspark_tpu.analysis.lockorder import _call_targets

    for key, lscan in lock_scans.items():
        if scans.get(key) is None:
            continue  # function itself is blocking-ok
        for call, held in lscan.held_calls:
            if blocking_reason(call) is not None:
                continue  # already reported as a direct finding
            for target in _call_targets(call, call_edges, key):
                found = blockers.get(target)
                if found is None:
                    continue
                reason, rel, line = found
                bscan = scans.get(key)
                if bscan is not None and bscan._exempt(call):
                    continue
                findings.append(
                    Finding(
                        "BL001",
                        lscan.mod.relpath,
                        call.lineno,
                        call.col_offset,
                        f"call to '{target[1]}' — which blocks "
                        f"({reason} at {rel}:{line}) — while holding "
                        f"{', '.join(short(h) for h in held)}",
                    )
                )
                break
    return findings
