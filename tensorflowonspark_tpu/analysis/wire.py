"""WR: the wire-schema static head — wirecheck's lint half.

The wire catalog (``cluster/wire.py``) is only a single source of
truth while nothing constructs or parses wire payloads behind its
back. These rules make that structural:

- **WR001** — raw wire construction or parsing outside the codec
  module: a dict literal carrying a declared message-kind ``"type"``
  tag (build it with ``wire.encode``); a string-literal subscript /
  ``.get`` on a value that came straight off the wire
  (``MessageSocket.receive(...)``, a ``mgr.get(<declared KV key>)``
  probe) — parse it with ``wire.decode`` first; or a ``mgr.set`` of a
  declared KV key whose payload is a raw dict/string literal instead
  of a ``wire.encode(...)`` call.
- **WR002** — an undeclared wire name: a message-kind literal absent
  from the catalog (in a ``"type"`` tag or compared against a
  ``wire.message_kind(...)`` result), or a manager-KV key string
  literal — undeclared keys must be declared in ``WIRE_SCHEMAS``;
  declared ones must be spelled via the ``cluster/wire.py`` constant,
  never inlined (the bare ``"feed_timeout"`` probe this family was
  built to catch).
- **WR003** — a field the declared schema does not have:
  ``wire.encode("<schema>", bogus=...)`` keywords, and
  ``d["bogus"]`` / ``d.get("bogus")`` reads on a value assigned from
  ``wire.decode("<schema>", ...)``.

Escape for a deliberate exception: ``# lint: wire-ok: <why>`` on the
flagged line (or the line above) — the justification is mandatory.

The catalog is a **pure literal** precisely so this analyzer can
``ast.literal_eval`` it without importing anything; the KV key
constants beside it (``NAME = _kv_key_of("kv.x")``) are resolved from
the same parse, so migrated call sites that spell
``mgr.get(FEED_KNOBS_KEY)`` are recognized as declared-key probes.
"""

from __future__ import annotations

import ast
import os
import re

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package

__all__ = ["check"]

WIRE_OK_RE = re.compile(r"#\s*lint:\s*wire-ok:\s*\S")

_WIRE_MODULE = "tensorflowonspark_tpu.cluster.wire"

# receivers whose .get/.set with a string-literal key is a manager-KV
# wire call (the repo-wide naming convention for ManagerHandle values)
_MGR_NAMES = {"mgr", "manager", "_mgr"}

# codec entry points whose first-argument schema name WR003 validates
_CODEC_FNS = {"encode", "decode"}

# bare-value codec schemas take codec-specific keywords, not fields
_SCALAR_KWS = {"value"}
_CURSOR_KWS = {"seq", "skip"}


class WireCatalog:
    """The declared catalog, AST-read from ``cfg.wire_module``."""

    def __init__(self, schemas: dict, key_consts: dict, parsed: bool):
        self.schemas = schemas  # name -> schema entry dict
        self.parsed = parsed
        self.kinds = {
            sc["kind"]
            for sc in schemas.values()
            if isinstance(sc.get("kind"), str)
        }
        self.kv_keys = {
            sc["kv_key"]: name
            for name, sc in schemas.items()
            if isinstance(sc.get("kv_key"), str)
        }
        # constant name -> kv key string (INGEST_PLAN_KEY = ...)
        self.key_consts = key_consts

    def fields(self, name: str) -> set | None:
        sc = self.schemas.get(name)
        if sc is None:
            return None
        out = set(sc.get("fields", ()))
        if sc.get("codec") == "scalar":
            out |= _SCALAR_KWS
        if sc.get("codec") == "cursor_entry":
            out |= _CURSOR_KWS
        return out


def _load_catalog(root: str, cfg: Config) -> WireCatalog:
    path = os.path.join(root, cfg.wire_module)
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return WireCatalog({}, {}, parsed=False)
    schemas: dict = {}
    key_consts: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "WIRE_SCHEMAS" in targets:
            try:
                schemas = ast.literal_eval(node.value)
            except (ValueError, TypeError, SyntaxError):
                return WireCatalog({}, {}, parsed=False)
        elif (
            len(targets) == 1
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Name)
            and node.value.func.id == "_kv_key_of"
            and node.value.args
            and isinstance(node.value.args[0], ast.Constant)
        ):
            key_consts[targets[0]] = node.value.args[0].value
    if not schemas:
        return WireCatalog({}, {}, parsed=False)
    # resolve constant names to actual key strings via the table
    resolved = {
        const: schemas[sname]["kv_key"]
        for const, sname in key_consts.items()
        if sname in schemas and "kv_key" in schemas[sname]
    }
    return WireCatalog(schemas, resolved, parsed=True)


def _has_escape(mod: Module, node: ast.AST) -> bool:
    end = getattr(node, "end_lineno", node.lineno) or node.lineno
    for line in range(max(1, node.lineno - 1), end + 1):
        c = mod.comments.get(line)
        if c and WIRE_OK_RE.search(c):
            return True
    return False


def _terminal_name(node: ast.AST) -> str | None:
    """``a.b.mgr`` → ``mgr``; ``mgr`` → ``mgr``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _attr_chain(node: ast.AST) -> str | None:
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Checker(ast.NodeVisitor):
    """One module's WR pass. Which names count as "the wire codec" is
    resolved from this module's imports (the FP001 pattern), so an
    unrelated local ``encode`` helper is never misread."""

    def __init__(self, mod: Module, cat: WireCatalog, is_wire_module: bool):
        self.mod = mod
        self.cat = cat
        self.is_wire_module = is_wire_module
        self.wire_mods: set = set()  # local names bound to the wire module
        self.wire_fns: dict = {}  # local name -> codec fn name
        self.findings: list = []
        # per-function taint state (reset by visit_FunctionDef)
        self._tainted: set = set()  # raw wire values (receive / kv probe)
        self._decoded: dict = {}  # var name -> schema name (wire.decode)
        # names assigned from wire.message_kind(...) — module-wide
        # (kind vars are short-lived dispatch locals; monotonic is fine)
        self._kind_vars: set = set()

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        if _has_escape(self.mod, node):
            return
        self.findings.append(
            Finding(rule, self.mod.relpath, node.lineno, node.col_offset, msg)
        )

    # -- import resolution ------------------------------------------------

    def visit_Import(self, node):
        for alias in node.names:
            if alias.name == _WIRE_MODULE:
                self.wire_mods.add(alias.asname or _WIRE_MODULE)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.level == 0 and node.module == _WIRE_MODULE:
            for alias in node.names:
                if alias.name in _CODEC_FNS:
                    self.wire_fns[alias.asname or alias.name] = alias.name
        elif node.level == 0 and node.module == _WIRE_MODULE.rsplit(".", 1)[0]:
            for alias in node.names:
                if alias.name == "wire":
                    self.wire_mods.add(alias.asname or "wire")
        self.generic_visit(node)

    def _codec_call(self, node: ast.Call) -> str | None:
        """'encode' / 'decode' / 'message_kind' when ``node`` calls the
        wire codec, else None."""
        func = node.func
        if isinstance(func, ast.Name):
            return self.wire_fns.get(func.id)
        if isinstance(func, ast.Attribute):
            base = _attr_chain(func.value)
            if base in self.wire_mods or base == _WIRE_MODULE:
                return func.attr
        return None

    # -- taint sources ----------------------------------------------------

    def _is_receive_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        chain = _attr_chain(node.func)
        return bool(chain) and chain.endswith("MessageSocket.receive")

    def _kv_key_of_arg(self, arg: ast.AST) -> str | None:
        """The declared KV key named by a .get/.set key argument —
        via literal or via a registry constant — else None."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value if arg.value in self.cat.kv_keys else None
        name = _terminal_name(arg)
        if name is not None:
            return self.cat.key_consts.get(name)
        return None

    def _is_kv_probe(self, node: ast.AST) -> bool:
        """``<mgr>.get(<declared key>)`` — a raw KV read."""
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and len(node.args) >= 1
            and _terminal_name(node.func.value) in _MGR_NAMES
            and self._kv_key_of_arg(node.args[0]) is not None
        )

    # -- per-function pass -------------------------------------------------

    def _function_pass(self, node) -> None:
        outer_t, outer_d = self._tainted, self._decoded
        self._tainted, self._decoded = set(), {}
        self.generic_visit(node)
        self._tainted, self._decoded = outer_t, outer_d

    visit_FunctionDef = _function_pass
    visit_AsyncFunctionDef = _function_pass

    def visit_Assign(self, node):
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            val = node.value
            if self._is_receive_call(val) or self._is_kv_probe(val):
                self._tainted.add(tgt)
            elif isinstance(val, ast.Call):
                fn = self._codec_call(val)
                if (
                    fn == "decode"
                    and val.args
                    and isinstance(val.args[0], ast.Constant)
                    and isinstance(val.args[0].value, str)
                ):
                    self._decoded[tgt] = val.args[0].value
                    self._tainted.discard(tgt)
                else:
                    self._tainted.discard(tgt)
                    self._decoded.pop(tgt, None)
            else:
                self._tainted.discard(tgt)
                self._decoded.pop(tgt, None)
        self.generic_visit(node)

    # -- field accesses ----------------------------------------------------

    def _field_access(self, node: ast.AST, var: str, field: str) -> None:
        if var in self._tainted and not self.is_wire_module:
            self._flag(
                "WR001", node,
                f"raw wire field read {var}[{field!r}] on an undecoded "
                "payload — route it through wire.decode(<schema>, ...) "
                "so the declared schema (and its compat gate) covers "
                "this consumer",
            )
        elif var in self._decoded:
            sname = self._decoded[var]
            fields = self.cat.fields(sname)
            if fields is not None and field not in fields:
                self._flag(
                    "WR003", node,
                    f"field {field!r} is not declared by wire schema "
                    f"'{sname}' — declare it in WIRE_SCHEMAS (and bump "
                    "the version per the compat policy) before reading "
                    "it",
                )

    def visit_Subscript(self, node):
        if (
            isinstance(node.value, ast.Name)
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
        ):
            self._field_access(node, node.value.id, node.slice.value)
        self.generic_visit(node)

    # -- calls -------------------------------------------------------------

    def visit_Call(self, node):
        func = node.func
        # d.get("field") on tainted/decoded values
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "get"
            and isinstance(func.value, ast.Name)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            self._field_access(node, func.value.id, node.args[0].value)
        # manager-KV calls: key discipline + raw-literal publishes
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("get", "set")
            and _terminal_name(func.value) in _MGR_NAMES
            and node.args
        ):
            self._kv_call(node, func)
        # wire.encode schema-name + keyword validation
        fn = self._codec_call(node)
        if fn in ("encode", "decode") and not self.is_wire_module:
            self._codec_fields(node, fn)
        self.generic_visit(node)

    def _kv_call(self, node: ast.Call, func: ast.Attribute) -> None:
        key = node.args[0]
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            if not self.is_wire_module:
                if key.value in self.cat.kv_keys:
                    self._flag(
                        "WR002", node,
                        f"bare manager-KV key literal {key.value!r} — "
                        "spell it via the cluster/wire.py registry "
                        "constant so every probe and publish of this "
                        "wire is greppable from one place",
                    )
                elif self.cat.parsed:
                    self._flag(
                        "WR002", node,
                        f"manager-KV key {key.value!r} is not declared "
                        "in cluster/wire.py WIRE_SCHEMAS — every "
                        "cross-process KV wire needs a declared schema "
                        "and key constant",
                    )
        if (
            func.attr == "set"
            and len(node.args) >= 2
            and self._kv_key_of_arg(key) is not None
            and not self.is_wire_module
        ):
            payload = node.args[1]
            if isinstance(payload, ast.Dict) or (
                isinstance(payload, ast.Constant)
                and isinstance(payload.value, str)
            ):
                self._flag(
                    "WR001", node,
                    "raw payload published to a declared KV wire — "
                    "construct it with wire.encode(<schema>, ...) so "
                    "the declared shape (and its golden-corpus gate) "
                    "covers this producer",
                )

    def _codec_fields(self, node: ast.Call, fn: str) -> None:
        if not (
            node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return
        sname = node.args[0].value
        fields = self.cat.fields(sname)
        if fields is None:
            if self.cat.parsed:
                self._flag(
                    "WR003", node,
                    f"wire.{fn} names undeclared schema {sname!r} — "
                    "declare it in cluster/wire.py WIRE_SCHEMAS",
                )
            return
        if fn == "encode":
            for kw in node.keywords:
                if kw.arg is not None and kw.arg not in fields:
                    self._flag(
                        "WR003", node,
                        f"field {kw.arg!r} is not declared by wire "
                        f"schema '{sname}' — declare it in WIRE_SCHEMAS "
                        "(and bump the version per the compat policy) "
                        "before writing it",
                    )

    # -- message dicts and kind literals -----------------------------------

    def visit_Dict(self, node):
        for k, v in zip(node.keys, node.values):
            if (
                isinstance(k, ast.Constant)
                and k.value == "type"
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)
            ):
                if self.is_wire_module:
                    continue
                if v.value in self.cat.kinds:
                    self._flag(
                        "WR001", node,
                        f"raw wire-message dict for kind {v.value!r} — "
                        "construct it with wire.encode(<schema>, ...) "
                        "so the declared shape (and its golden-corpus "
                        "gate) covers this producer",
                    )
                elif self.cat.parsed:
                    self._flag(
                        "WR002", node,
                        f"message kind {v.value!r} is not declared in "
                        "cluster/wire.py WIRE_SCHEMAS — every "
                        "cross-process message kind needs a declared "
                        "schema",
                    )
        self.generic_visit(node)

    def visit_Compare(self, node):
        # <kind var from wire.message_kind(...)> == "<literal>"
        if (
            isinstance(node.left, ast.Name)
            and node.left.id in self._kind_vars
            and len(node.comparators) == 1
            and isinstance(node.comparators[0], ast.Constant)
            and isinstance(node.comparators[0].value, str)
            and self.cat.parsed
            and node.comparators[0].value not in self.cat.kinds
        ):
            self._flag(
                "WR002", node,
                f"message kind {node.comparators[0].value!r} is not "
                "declared in cluster/wire.py WIRE_SCHEMAS — a dispatch "
                "arm for it can never match a sanctioned producer",
            )
        self.generic_visit(node)


def _track_kind_vars(checker: _Checker, tree: ast.AST) -> None:
    """Pre-pass: collect names assigned from ``wire.message_kind(...)``
    so Compare checks work regardless of visit order."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and checker._codec_call(node.value) == "message_kind"
        ):
            checker._kind_vars.add(node.targets[0].id)


def check(pkg: Package, cfg: Config) -> list:
    cat = _load_catalog(pkg.root, cfg)
    wire_rel = cfg.wire_module.replace(os.sep, "/")
    findings: list = []
    for mod in pkg.modules:
        checker = _Checker(mod, cat, is_wire_module=(mod.relpath == wire_rel))
        # imports first so the kind-var pre-pass can resolve the codec
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Import):
                checker.visit_Import(n)
            elif isinstance(n, ast.ImportFrom):
                checker.visit_ImportFrom(n)
        _track_kind_vars(checker, mod.tree)
        checker.visit(mod.tree)
        findings.extend(checker.findings)
    return findings
