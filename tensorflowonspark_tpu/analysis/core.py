"""Lint driver: config, file discovery, baseline compare, reporting.

The analyzers themselves live in :mod:`.locks`, :mod:`.jaxapi` and
:mod:`.hostsync`; this module parses every file ONCE into a
:class:`Package` (source + AST + comment map per module) and hands that
to each analyzer, so a whole-package run costs one parse pass plus three
tree walks — well inside the tier-1 <30 s budget.

Baseline semantics (ratchet, not allowlist): findings are keyed by
``(rule, path, message)`` — deliberately NOT by line number, so an
unrelated edit shifting lines doesn't invalidate the baseline — and
compared as multisets. A finding over the baselined count for its key
fails the run; a baselined key with fewer current findings is reported
as stale so the baseline only ever shrinks.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize
from typing import Any, Iterable

__all__ = [
    "Config",
    "Finding",
    "Module",
    "Package",
    "load_config",
    "main",
    "run_lint",
]

DEFAULT_RULES = (
    "LK", "JX", "HS", "TL", "FP", "PF", "OB", "BL", "TH", "SH", "AT", "WR",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str

    def key(self) -> tuple:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclasses.dataclass
class Config:
    paths: tuple = ("tensorflowonspark_tpu",)
    baseline: str | None = "tools/tfoslint_baseline.json"
    rules: tuple = DEFAULT_RULES
    # LK/JX/HS knobs (see each analyzer module)
    compat_module: str = "tensorflowonspark_tpu/utils/compat.py"
    failpoints_module: str = "tensorflowonspark_tpu/utils/failpoints.py"
    # the EVENTS catalog OB002 validates flightrec.note names against
    flightrec_module: str = "tensorflowonspark_tpu/obs/flightrec.py"
    # the TUNABLE_ATTRS/SANCTIONED literals AT001 enforces
    autotune_module: str = "tensorflowonspark_tpu/autotune/registry.py"
    # the declarative layout table the SH rules enforce (analysis/sharding.py)
    layout_module: str = "tensorflowonspark_tpu/compute/layout.py"
    # the declarative wire catalog the WR rules enforce (analysis/wire.py)
    wire_module: str = "tensorflowonspark_tpu/cluster/wire.py"
    moved_jax_symbols: tuple = ("shard_map", "lax.axis_size")
    hot_roots: tuple = (
        "tensorflowonspark_tpu/serving/engine.py::ContinuousBatcher._loop",
        "tensorflowonspark_tpu/compute/train.py::build_train_step",
    )
    exclude: tuple = ()


_SECTION_RE = re.compile(r"^\s*\[(?P<name>[^]]+)\]\s*$")


def _parse_toml_value(text: str) -> Any:
    """Parse the value subset [tool.tfoslint] uses: strings, booleans,
    ints, and (possibly multiline, already-joined) string arrays."""
    text = text.strip()
    if text.startswith("["):
        inner = text[1:-1] if text.endswith("]") else text[1:]
        items = []
        for part in inner.split(","):
            part = part.strip()
            if part:
                items.append(_parse_toml_value(part))
        return items
    if text.startswith(('"', "'")):
        return text[1:-1]
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        return text


def _read_tool_section(pyproject_path: str) -> dict:
    """Read ``[tool.tfoslint]`` from pyproject.toml.

    Uses :mod:`tomllib` when available (3.11+); this environment runs
    3.10, so a fallback parser handles the flat key/value + string-array
    subset the section actually uses.
    """
    try:
        with open(pyproject_path, "rb") as f:
            raw = f.read()
    except OSError:
        return {}
    try:
        import tomllib  # noqa: PLC0415 - py311+

        return (
            tomllib.loads(raw.decode("utf-8"))
            .get("tool", {})
            .get("tfoslint", {})
        )
    except ImportError:
        pass
    out: dict = {}
    in_section = False
    pending_key = None
    pending_val = ""
    for line in raw.decode("utf-8").splitlines():
        m = _SECTION_RE.match(line)
        if m:
            in_section = m.group("name").strip() == "tool.tfoslint"
            pending_key = None
            continue
        if not in_section:
            continue
        stripped = line.split("#", 1)[0].rstrip()
        if not stripped.strip():
            continue
        if pending_key is not None:
            pending_val += " " + stripped.strip()
            if stripped.rstrip().endswith("]"):
                out[pending_key] = _parse_toml_value(pending_val)
                pending_key = None
            continue
        if "=" not in stripped:
            continue
        key, _, val = stripped.partition("=")
        key, val = key.strip(), val.strip()
        if val.startswith("[") and not val.endswith("]"):
            pending_key, pending_val = key, val  # multiline array
            continue
        out[key] = _parse_toml_value(val)
    return out


def load_config(root: str, pyproject: str | None = None) -> Config:
    """Build a :class:`Config` from ``[tool.tfoslint]`` (defaults where
    the section or a key is absent). ``root`` is the repo root every
    relative path in the section resolves against."""
    section = _read_tool_section(
        pyproject or os.path.join(root, "pyproject.toml")
    )
    cfg = Config()
    if "paths" in section:
        cfg.paths = tuple(section["paths"])
    if "baseline" in section:
        cfg.baseline = section["baseline"] or None
    if "rules" in section:
        cfg.rules = tuple(section["rules"])
    if "compat_module" in section:
        cfg.compat_module = section["compat_module"]
    if "failpoints_module" in section:
        cfg.failpoints_module = section["failpoints_module"]
    if "flightrec_module" in section:
        cfg.flightrec_module = section["flightrec_module"]
    if "autotune_module" in section:
        cfg.autotune_module = section["autotune_module"]
    if "layout_module" in section:
        cfg.layout_module = section["layout_module"]
    if "wire_module" in section:
        cfg.wire_module = section["wire_module"]
    if "moved_jax_symbols" in section:
        cfg.moved_jax_symbols = tuple(section["moved_jax_symbols"])
    if "hot_roots" in section:
        cfg.hot_roots = tuple(section["hot_roots"])
    if "exclude" in section:
        cfg.exclude = tuple(section["exclude"])
    return cfg


@dataclasses.dataclass
class Module:
    path: str  # absolute
    relpath: str  # repo-relative, forward slashes
    name: str  # dotted module name when under a package, else stem
    tree: ast.AST
    source: str
    comments: dict  # {line: comment text}


class Package:
    """Every parsed module of one lint run, plus the repo root they are
    relative to. Analyzers share this so each file parses once."""

    def __init__(self, root: str, modules: list[Module]):
        self.root = root
        self.modules = modules
        self.by_relpath = {m.relpath: m for m in modules}


def _comment_map(source: str) -> dict:
    out: dict = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass
    return out


def _module_name(relpath: str) -> str:
    parts = relpath[:-3].split("/")  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _discover(root: str, paths: Iterable[str], exclude: Iterable[str]) -> list:
    files = []
    exclude = tuple(exclude)
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            files.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [
                d for d in sorted(dirnames) if d != "__pycache__"
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    out = []
    for f in files:
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        if any(rel.startswith(e.rstrip("/")) for e in exclude):
            continue
        out.append((f, rel))
    return out


def parse_package(root: str, cfg: Config) -> tuple:
    """Parse every file under ``cfg.paths`` → (Package, parse-error
    findings). A file that does not parse is itself a finding (rule
    ``E000``), not a crash — the lint must degrade per-file."""
    modules: list[Module] = []
    errors: list[Finding] = []
    for path, rel in _discover(root, cfg.paths, cfg.exclude):
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            line = getattr(e, "lineno", 0) or 0
            errors.append(
                Finding("E000", rel, line, 0, f"file does not parse: {e}")
            )
            continue
        modules.append(
            Module(path, rel, _module_name(rel), tree, src, _comment_map(src))
        )
    return Package(root, modules), errors


def run_lint(root: str, cfg: Config) -> list:
    """Run every enabled analyzer over the package; findings sorted by
    (path, line, rule)."""
    from tensorflowonspark_tpu.analysis import (
        autotune as autotune_rule,
        blocking,
        failpoints as fp_rule,
        flightrecnames,
        hostsync,
        jaxapi,
        lockorder,
        locks,
        obsmetrics,
        prefetchrule,
        sharding as sharding_rule,
        wire as wire_rule,
    )

    pkg, findings = parse_package(root, cfg)
    enabled = set(cfg.rules)
    # the tfsan static head (LK003 + BL001) shares one package walk
    shared = (
        lockorder.scan_functions(pkg)
        if {"LK", "BL"} & enabled
        else None
    )
    if "LK" in enabled:
        findings.extend(locks.check(pkg))
        findings.extend(lockorder.check_lock_order(pkg, shared))
    if "BL" in enabled:
        findings.extend(blocking.check(pkg, cfg, shared))
    if "TH" in enabled:
        findings.extend(lockorder.check_threads(pkg))
    if "JX" in enabled:
        findings.extend(jaxapi.check(pkg, cfg))
    if "SH" in enabled:
        findings.extend(sharding_rule.check(pkg, cfg))
    if "WR" in enabled:
        findings.extend(wire_rule.check(pkg, cfg))
    if "FP" in enabled:
        findings.extend(fp_rule.check(pkg, cfg))
    if "AT" in enabled:
        findings.extend(autotune_rule.check(pkg, cfg))
    if "PF" in enabled:
        findings.extend(prefetchrule.check(pkg, cfg))
    if "OB" in enabled:
        findings.extend(obsmetrics.check(pkg, cfg))
        findings.extend(flightrecnames.check(pkg, cfg))
    if {"HS", "TL"} & enabled:
        findings.extend(
            hostsync.check(
                pkg,
                cfg,
                host_sync="HS" in enabled,
                tracer_leak="TL" in enabled,
            )
        )
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


# -- baseline ---------------------------------------------------------------


def load_baseline(path: str) -> dict:
    """{key: count} from a baseline file; missing file = empty baseline."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return {}
    out: dict = {}
    for e in data.get("entries", []):
        key = (e["rule"], e["path"], e["message"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def write_baseline(path: str, findings: list) -> None:
    counts: dict = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {
            "rule": rule,
            "path": p,
            "message": msg,
            "count": n,
            "justification": "",
        }
        for (rule, p, msg), n in sorted(counts.items())
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2)
        f.write("\n")


def apply_baseline(findings: list, baseline: dict) -> tuple:
    """Split findings into (new, suppressed) against {key: count}, and
    report stale baseline keys (allowed more than observed)."""
    remaining = dict(baseline)
    new, suppressed = [], []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            suppressed.append(f)
        else:
            new.append(f)
    stale = sorted(
        (k, n) for k, n in remaining.items() if n > 0
    )
    return new, suppressed, stale


# -- CLI --------------------------------------------------------------------


def main(argv: list | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tfoslint",
        description="repo-native static analysis: lock discipline, "
        "jax API hygiene, host-sync/tracer leaks",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint "
                    "(default: [tool.tfoslint] paths)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: cwd, or the pyproject.toml "
                    "directory walking up from it)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: [tool.tfoslint] baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to the baseline file "
                    "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule families to run "
                    "(default: [tool.tfoslint] rules)")
    args = ap.parse_args(argv)

    root = args.root or os.getcwd()
    probe = root
    while not os.path.exists(os.path.join(probe, "pyproject.toml")):
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    if os.path.exists(os.path.join(probe, "pyproject.toml")):
        root = probe

    cfg = load_config(root)
    if args.paths:
        cfg.paths = tuple(args.paths)
    if args.rules:
        cfg.rules = tuple(
            r.strip().upper() for r in args.rules.split(",") if r.strip()
        )
    baseline_path = args.baseline or cfg.baseline
    if baseline_path and not os.path.isabs(baseline_path):
        baseline_path = os.path.join(root, baseline_path)

    findings = run_lint(root, cfg)

    if args.write_baseline:
        if not baseline_path:
            print("tfoslint: no baseline path configured", file=sys.stderr)
            return 2
        write_baseline(baseline_path, findings)
        print(
            f"tfoslint: wrote {len(findings)} finding(s) to "
            f"{os.path.relpath(baseline_path, root)}"
        )
        return 0

    baseline = (
        {}
        if (args.no_baseline or not baseline_path)
        else load_baseline(baseline_path)
    )
    new, suppressed, stale = apply_baseline(findings, baseline)
    for f in new:
        print(f.render())
    if suppressed:
        print(f"tfoslint: {len(suppressed)} baselined finding(s) suppressed")
    for (rule, path, msg), n in stale:
        print(
            f"tfoslint: stale baseline entry ({n} unused): "
            f"{rule} {path}: {msg}"
        )
    if new:
        print(f"tfoslint: {len(new)} new violation(s)")
        return 1
    print(f"tfoslint: clean ({len(findings)} finding(s), all baselined)")
    return 0
