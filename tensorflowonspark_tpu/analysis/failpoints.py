"""FP001: failpoint sites must be registered string literals.

The failpoint registry (``utils/failpoints.py``) is only trustworthy if
every ``failpoint("...")`` call site names a site that actually exists
in :data:`SITES`: ``TFOS_FAILPOINTS=resevration.register=raise`` armed
against a typo'd call site would silently no-op — the chaos run reports
green while injecting nothing. ``arm()`` validates the arming side at
runtime; this rule validates the CALL side at build time:

- a ``failpoint(...)`` call whose first argument is not a plain string
  literal (f-strings, variables, concatenation) is flagged — dynamic
  names defeat both this check and grep;
- a literal name missing from the registry's ``SITES`` set is flagged.

The registry is read from ``cfg.failpoints_module`` (parsed standalone
from disk, so fixture runs that lint only a test directory still
validate against the real registry).
"""

from __future__ import annotations

import ast
import os

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package

__all__ = ["check"]

_FP_MODULE = "tensorflowonspark_tpu.utils.failpoints"


def _registered_sites(root: str, cfg: Config) -> set | None:
    """The SITES literal from the registry module, or None when it
    cannot be read (the rule then only enforces literalness)."""
    path = os.path.join(root, cfg.failpoints_module)
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "SITES"
            for t in node.targets
        ):
            continue
        consts = {
            n.value
            for n in ast.walk(node.value)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
        }
        if consts:
            return consts
    return None


class _Checker(ast.NodeVisitor):
    """Flags bad ``failpoint(...)`` calls. Which names/attributes count
    as "the failpoint function" is resolved from this module's imports,
    so a user-defined helper that happens to be called ``failpoint``
    in unrelated code is not flagged."""

    def __init__(self, mod: Module, sites: set | None):
        self.mod = mod
        self.sites = sites
        self.fn_names: set = set()  # local names bound to the function
        self.mod_names: set = set()  # local names bound to the module
        self.findings: list = []

    def _flag(self, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(
                "FP001", self.mod.relpath, node.lineno, node.col_offset, msg
            )
        )

    def visit_Import(self, node):
        for alias in node.names:
            if alias.name == _FP_MODULE:
                # `import pkg.utils.failpoints` binds the ROOT package
                # name; calls then spell the full dotted chain, which
                # the Attribute branch below resolves
                self.mod_names.add(alias.asname or _FP_MODULE)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.level == 0 and node.module == _FP_MODULE:
            for alias in node.names:
                if alias.name == "failpoint":
                    self.fn_names.add(alias.asname or alias.name)
        elif node.level == 0 and node.module == _FP_MODULE.rsplit(".", 1)[0]:
            for alias in node.names:
                if alias.name == "failpoints":
                    self.mod_names.add(alias.asname or alias.name)
        self.generic_visit(node)

    def _is_failpoint_call(self, func: ast.AST) -> bool:
        if isinstance(func, ast.Name):
            return func.id in self.fn_names
        if isinstance(func, ast.Attribute) and func.attr == "failpoint":
            parts: list = []
            base = func.value
            while isinstance(base, ast.Attribute):
                parts.append(base.attr)
                base = base.value
            if isinstance(base, ast.Name):
                parts.append(base.id)
                dotted = ".".join(reversed(parts))
                return dotted in self.mod_names or dotted == _FP_MODULE
        return False

    def visit_Call(self, node):
        if self._is_failpoint_call(node.func):
            arg = node.args[0] if node.args else None
            if not (
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            ):
                self._flag(
                    node,
                    "failpoint site must be a string literal (dynamic "
                    "names defeat the registered-site check and make "
                    "TFOS_FAILPOINTS un-greppable)",
                )
            elif self.sites is not None and arg.value not in self.sites:
                self._flag(
                    node,
                    f"failpoint site '{arg.value}' is not registered in "
                    "utils/failpoints.py SITES — an armed spec for it "
                    "would silently no-op",
                )
        self.generic_visit(node)


def check(pkg: Package, cfg: Config) -> list:
    sites = _registered_sites(pkg.root, cfg)
    findings: list = []
    for mod in pkg.modules:
        checker = _Checker(mod, sites)
        checker.visit(mod.tree)
        findings.extend(checker.findings)
    return findings
