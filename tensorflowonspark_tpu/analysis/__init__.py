"""tfoslint: repo-native static analysis for the failure classes this
stack actually has.

Large distributed ML systems catch host/device-coordination and
concurrency bugs with build-time validation, not code review (the
TensorFlow system paper's reliability story; tf.data's account of feed
path concurrency). This package is that layer for tensorflowonspark_tpu,
three AST analyzers over the whole package, run in CI against a
checked-in baseline so any NEW violation fails the build:

- **LK (lock discipline)** — shared mutable attributes are annotated
  ``# guarded-by: self._lock`` at their assignment site; every other
  read/write of that attribute must sit lexically inside a
  ``with <that lock>:`` block (or a function marked ``# lint:
  holds-lock``). Catches the unsynchronized-shared-state races the
  advisor rounds kept finding (e.g. the ``warmup()`` shared-knob
  mutation class).
- **JX (jax API hygiene)** — ``jax._src`` / ``jax.interpreters`` are
  hard errors anywhere; version-moved symbols (``shard_map``) must be
  imported from the guarded shims in ``utils/compat.py``. Catches the
  AttributeError-at-collection env drift the ring/ulysses/mesh-flash
  paths shipped with.
- **HS/TL (host sync + tracer leaks)** — implicit device→host syncs
  (``.item()``, ``float()``/``int()`` on device values, ``np.asarray``
  on jax values) flagged inside functions reachable from the serving
  engine ``_loop`` and ``train.step`` hot paths; storing values on
  ``self`` or module globals inside ``jit``-decorated functions flagged
  everywhere (a traced value outliving its trace is a leak).
- **SH (sharding/layout — shardcheck static head)** — every
  ``PartitionSpec``/``NamedSharding`` must come from the declarative
  layout table (``compute/layout.py``; escape ``# lint: layout-ok:
  <why>``), spec axis names must be declared in ``MESH_AXES``, jits on
  the hot call graph must carry ``in_shardings``/donation for large
  array params, and literal ``with_sharding_constraint`` specs must
  match a table rule. The matching TRACE head is
  ``analysis/shardcheck.py`` + ``tools/shardcheck.py`` (collective
  census of the lowered train step vs a committed baseline).
- **tfsan static head (LK003/BL001/TH001)** — lock-acquisition-order
  cycles inferred from nested ``with lock:`` scopes across the package
  call graph (potential ABBA deadlocks), provably-blocking calls made
  under a lock or while a columnar frame view is live (the DESIGN.md
  liveness rules, mechanized), and non-daemon threads never
  ``join(timeout=)``-ed. ``tools/tfsan.py`` runs exactly these; the
  matching RUNTIME head is ``utils/lockwitness.py`` (``TFOS_TFSAN=1``).

Run it::

    python tools/tfoslint.py tensorflowonspark_tpu/

Configuration lives in ``pyproject.toml`` under ``[tool.tfoslint]``;
known-and-justified findings live in the baseline file
(``tools/tfoslint_baseline.json``). See ``docs/STATIC_ANALYSIS.md``.
"""

from tensorflowonspark_tpu.analysis.core import (  # noqa: F401
    Config,
    Finding,
    Package,
    load_config,
    main,
    run_lint,
)
