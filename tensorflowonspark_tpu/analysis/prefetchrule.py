"""PF001: raw ``feed.next_batch`` feeding a jitted step inside a loop.

The pattern

.. code-block:: python

    while not feed.should_stop():
        batch = feed.next_batch(bs)
        state, loss = step(state, batch)   # step is jitted

serializes the feed pull + host columnize + H2D transfer with the device
step: the accelerator idles through the whole input path every
iteration. ``feed.prefetch.DevicePrefetcher`` (``from_feed``) moves the
pull/stage/transfer onto a producer thread so batch N+1's input cost
hides behind step N's compute — measured on this repo's tunneled chip a
transfer-bound loop dropped from ~432 ms to ~36 ms per iteration.

Heuristic (deliberately narrow, near-zero FP):

- "jitted step" = a name bound from ``jax.jit(...)`` / ``jit(...)``, a
  function decorated with ``@jax.jit`` (bare or via ``functools.partial``),
  or a name bound from the repo's jit-returning factory
  ``build_train_step(...)``. Names are collected module-wide.
- a ``For``/``While`` loop whose own body (nested defs excluded — a
  producer generator for a prefetcher is the FIX, not a violation) both
  calls ``<expr>.next_batch(...)`` and calls a jitted name is flagged at
  the ``next_batch`` call.

Suppress a justified site with a baseline entry (ratchet semantics) —
e.g. a debug loop where overlap is deliberately disabled.
"""

from __future__ import annotations

import ast

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package

__all__ = ["check"]

_JIT_FACTORIES = {"build_train_step"}


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for an Attribute/Name chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` / ``partial(jax.jit, ...)`` /
    ``build_train_step(...)`` (the repo's jit-returning factory)."""
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func)
    if name is None:
        return False
    tail = name.rsplit(".", 1)[-1]
    if tail == "jit" or tail in _JIT_FACTORIES:
        return True
    if tail == "partial" and node.args:
        inner = _dotted(node.args[0])
        return inner is not None and inner.rsplit(".", 1)[-1] == "jit"
    return False


def _jitted_names(tree: ast.AST) -> set:
    """Module-wide names that hold a jitted callable."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if _is_jit_expr(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                name = _dotted(dec)
                if _is_jit_expr(dec) or (
                    name is not None and name.rsplit(".", 1)[-1] == "jit"
                ):
                    out.add(node.name)
    return out


def _loop_body_nodes(loop: ast.AST):
    """Nodes of a loop body, not descending into nested function defs
    (a producer generator inside the loop is the prefetcher pattern)."""
    stack = list(ast.iter_child_nodes(loop))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check(pkg: Package, cfg: Config) -> list:
    findings: list = []
    for mod in pkg.modules:
        jitted = _jitted_names(mod.tree)
        if not jitted:
            continue
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            next_batch_calls: list = []
            step_called = False
            for node in _loop_body_nodes(loop):
                if not isinstance(node, ast.Call):
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "next_batch"
                ):
                    next_batch_calls.append(node)
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in jitted
                ):
                    step_called = True
            if step_called:
                for call in next_batch_calls:
                    findings.append(
                        Finding(
                            "PF001",
                            mod.relpath,
                            call.lineno,
                            call.col_offset,
                            "raw feed.next_batch() feeds a jitted step in "
                            "this loop — the device idles through the pull "
                            "+ columnize + H2D every iteration; route the "
                            "feed through feed.prefetch.DevicePrefetcher "
                            "(from_feed) so transfer overlaps step compute",
                        )
                    )
    return findings
