"""JX001/JX002: jax private and version-moved API gate.

- **JX001** — any import of (or attribute reach into) ``jax._src`` or
  ``jax.interpreters``. These are private namespaces with no stability
  contract; every jax upgrade this repo has lived through broke at least
  one of them (the ring/ulysses/mesh-flash collection errors at seed).
  Hard error everywhere, including the compat module: the shims wrap
  MOVED public symbols, they do not launder private ones.
- **JX002** — direct use of a version-moved symbol (configured in
  ``[tool.tfoslint] moved_jax_symbols``; today: ``shard_map``, which is
  top-level ``jax.shard_map`` on new jax and
  ``jax.experimental.shard_map.shard_map`` on 0.4.x). Either spelling
  outside ``utils/compat.py`` is an error — call sites must import the
  guarded shim so one module owns the version probe and the fallback.
"""

from __future__ import annotations

import ast

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package

__all__ = ["check"]

_PRIVATE_PREFIXES = ("jax._src", "jax.interpreters")


def _is_private(module: str) -> bool:
    return any(
        module == p or module.startswith(p + ".")
        for p in _PRIVATE_PREFIXES
    )


def _attr_chain(node: ast.AST) -> str | None:
    """Dotted name for ``a.b.c`` attribute chains rooted at a Name."""
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _moved_paths(sym: str) -> set:
    """Every dotted spelling of a moved symbol we refuse outside compat.

    ``sym`` is jax-relative: ``shard_map`` covers top-level
    ``jax.shard_map`` plus the legacy ``jax.experimental.shard_map``
    module (and its re-exported function); a dotted ``lax.axis_size``
    covers ``jax.lax.axis_size``.
    """
    paths = {f"jax.{sym}"}
    if "." not in sym:
        paths.add(f"jax.experimental.{sym}")
        paths.add(f"jax.experimental.{sym}.{sym}")
    return paths


class _Checker(ast.NodeVisitor):
    def __init__(self, mod: Module, cfg: Config, is_compat: bool):
        self.mod = mod
        self.moved = {
            sym: _moved_paths(sym) for sym in cfg.moved_jax_symbols
        }
        self.is_compat = is_compat
        self.findings: list = []

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(rule, self.mod.relpath, node.lineno, node.col_offset, msg)
        )

    def _check_module_path(self, node: ast.AST, module: str) -> None:
        if _is_private(module):
            self._flag(
                "JX001",
                node,
                f"import of private jax namespace '{module}' (no "
                "stability contract; route through utils/compat.py "
                "public-API shims)",
            )
        elif not self.is_compat:
            for sym, paths in self.moved.items():
                if module in paths:
                    self._flag(
                        "JX002",
                        node,
                        f"version-moved jax symbol '{sym}' imported "
                        "directly; import it from "
                        "tensorflowonspark_tpu.utils.compat",
                    )
                    return

    def visit_Import(self, node):
        for alias in node.names:
            self._check_module_path(node, alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module and node.level == 0:
            for alias in node.names:
                full = f"{node.module}.{alias.name}"
                if _is_private(full):
                    self._flag(
                        "JX001",
                        node,
                        f"import of private jax namespace '{full}' (no "
                        "stability contract; route through "
                        "utils/compat.py public-API shims)",
                    )
                    return
                if not self.is_compat:
                    for sym, paths in self.moved.items():
                        if full in paths:
                            self._flag(
                                "JX002",
                                node,
                                f"version-moved jax symbol '{sym}' "
                                "imported directly; import it from "
                                "tensorflowonspark_tpu.utils.compat",
                            )
                            return
            self._check_module_path(node, node.module)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        chain = _attr_chain(node)
        if chain:
            if _is_private(chain):
                self._flag(
                    "JX001",
                    node,
                    f"attribute reach into private jax namespace "
                    f"'{chain}'",
                )
                return  # one finding per chain, not per sub-attribute
            if not self.is_compat:
                for sym, paths in self.moved.items():
                    # `lax.axis_size` (a dotted sym used through
                    # `from jax import lax`) matches with or without
                    # the leading `jax.`
                    if chain in paths or ("." in sym and chain == sym):
                        self._flag(
                            "JX002",
                            node,
                            f"version-moved jax symbol '{chain}' used "
                            "directly; use "
                            "tensorflowonspark_tpu.utils.compat."
                            f"{sym.rsplit('.', 1)[-1]}",
                        )
                        return
        self.generic_visit(node)


def check(pkg: Package, cfg: Config) -> list:
    findings: list = []
    for mod in pkg.modules:
        checker = _Checker(mod, cfg, mod.relpath == cfg.compat_module)
        checker.visit(mod.tree)
        findings.extend(checker.findings)
    return findings
