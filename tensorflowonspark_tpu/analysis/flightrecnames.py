"""OB002: flight-recorder event names must be registered literals.

Postmortem tooling greps flight-recorder dumps by exact event kind
(``"fleet_shed"``, ``"slo_breach"``, ...), and tests assert on them;
a ``flightrec.note("flet_shed", ...)`` typo records an event nobody
will ever query — the black box silently loses the incident it existed
to capture. ``note()`` cannot validate at runtime (it must never raise,
and a registry check on every hot-path call would be pure overhead), so
the check runs at build time, the FP001 pattern applied to events:

- a ``note(...)`` call whose event argument is not a plain string
  literal is flagged — with ONE structured exception: a conditional
  expression (``"a" if cond else "b"``) whose branches are BOTH
  registered literals, which keeps the names greppable;
- a literal name missing from the catalog is flagged.

The catalog is the ``EVENTS`` frozenset in ``cfg.flightrec_module``,
parsed standalone from disk (fixture runs that lint only a test
directory still validate against the real catalog). Only names resolved
to the flightrec module via this module's imports are checked — an
unrelated ``rec.note(kind, ...)`` on some other object is not an event
emission. ``dump_now()`` reasons are deliberately out of scope: they
are free-form "why this dump was cut" text, not a queryable stream.
"""

from __future__ import annotations

import ast
import os

from tensorflowonspark_tpu.analysis.core import Config, Finding, Module, Package

__all__ = ["check"]

_FR_MODULE = "tensorflowonspark_tpu.obs.flightrec"


def _registered_events(root: str, cfg: Config) -> set | None:
    """The EVENTS literal from the flightrec module, or None when it
    cannot be read (the rule then only enforces literalness)."""
    path = os.path.join(root, cfg.flightrec_module)
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "EVENTS"
            for t in node.targets
        ):
            continue
        consts = {
            n.value
            for n in ast.walk(node.value)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
        }
        if consts:
            return consts
    return None


class _Checker(ast.NodeVisitor):
    """Flags bad ``note(...)`` calls. Which names count as "the
    flightrec note function" is resolved from this module's imports —
    method calls on arbitrary objects (``rec.note(kind, ...)`` inside
    the recorder itself, a queue's ``note``) are not event emissions."""

    def __init__(self, mod: Module, events: set | None):
        self.mod = mod
        self.events = events
        self.fn_names: set = set()  # local names bound to note()
        self.mod_names: set = set()  # local names bound to the module
        self.findings: list = []

    def _flag(self, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(
                "OB002", self.mod.relpath, node.lineno, node.col_offset, msg
            )
        )

    def visit_Import(self, node):
        for alias in node.names:
            if alias.name == _FR_MODULE:
                self.mod_names.add(alias.asname or _FR_MODULE)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.level == 0 and node.module == _FR_MODULE:
            for alias in node.names:
                if alias.name == "note":
                    self.fn_names.add(alias.asname or alias.name)
        elif node.level == 0 and node.module == _FR_MODULE.rsplit(".", 1)[0]:
            for alias in node.names:
                if alias.name == "flightrec":
                    self.mod_names.add(alias.asname or alias.name)
        self.generic_visit(node)

    def _is_note_call(self, func: ast.AST) -> bool:
        if isinstance(func, ast.Name):
            return func.id in self.fn_names
        if isinstance(func, ast.Attribute) and func.attr == "note":
            parts: list = []
            base = func.value
            while isinstance(base, ast.Attribute):
                parts.append(base.attr)
                base = base.value
            if isinstance(base, ast.Name):
                parts.append(base.id)
                dotted = ".".join(reversed(parts))
                return dotted in self.mod_names or dotted == _FR_MODULE
        return False

    def _check_literal(self, node: ast.Call, value: str) -> None:
        if self.events is not None and value not in self.events:
            self._flag(
                node,
                f"flightrec event '{value}' is not registered in "
                "obs/flightrec.py EVENTS — postmortem tooling grepping "
                "the catalog will never find it",
            )

    def visit_Call(self, node):
        if self._is_note_call(node.func):
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                self._check_literal(node, arg.value)
            elif (
                isinstance(arg, ast.IfExp)
                and isinstance(arg.body, ast.Constant)
                and isinstance(arg.body.value, str)
                and isinstance(arg.orelse, ast.Constant)
                and isinstance(arg.orelse.value, str)
            ):
                # "a" if cond else "b": both arms stay greppable —
                # validate each against the catalog
                self._check_literal(node, arg.body.value)
                self._check_literal(node, arg.orelse.value)
            else:
                self._flag(
                    node,
                    "flightrec event name must be a string literal (or "
                    "a conditional between two literals) — dynamic "
                    "names defeat the registered-event check and make "
                    "dumps un-greppable",
                )
        self.generic_visit(node)


def check(pkg: Package, cfg: Config) -> list:
    events = _registered_events(pkg.root, cfg)
    findings: list = []
    for mod in pkg.modules:
        checker = _Checker(mod, events)
        checker.visit(mod.tree)
        findings.extend(checker.findings)
    return findings
