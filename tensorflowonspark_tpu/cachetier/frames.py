"""Shared frame cache — the training plane's cachetier client.

N co-located readers (grain ``ColumnarFrameDataSource`` workers,
``ShardReader``/``IngestFeed`` drains) over one columnar dataset used
to cost N full passes over backing storage. :class:`FrameCache` fronts
the cachetier ``frames`` namespace so each frame is fetched from
backing storage ONCE — the read-through pread happens in the service
(:meth:`~.service.CacheTier.get_frame`), and every subsequent reader
gets the cached bytes.

Coherence is trivial by construction: ``scan_frames`` header offsets
over immutable frame files are the key space (``frame_key``), and a
frame's bytes at ``(path, off, span)`` never change once written.

Failure is a fallback, never an error: :meth:`get` returns None on any
cache-side problem (service down, timeout, dropped lookup+failed
backing read) and the caller reads its local mmap/pread path exactly
as it did before the cache existed.
"""

from __future__ import annotations

import logging
from typing import Any

logger = logging.getLogger(__name__)

__all__ = ["FrameCache"]


class FrameCache:
    """Reader-facing facade over a cachetier client (``LocalClient`` or
    ``CacheClient``) for the ``frames`` namespace."""

    def __init__(self, client: Any, *, timeout_s: float = 0.5):
        self.client = client
        self.timeout_s = float(timeout_s)

    def get(self, path: str, off: int, span: int) -> bytes | None:
        """One frame's bytes via the cache tier, or None (caller falls
        back to its local read path). Never raises."""
        try:
            return self.client.get_frame(
                path, int(off), int(span), timeout_s=self.timeout_s
            )
        except Exception:  # noqa: BLE001 - cache failure = local fallback
            logger.warning("frame cache get failed", exc_info=True)
            return None

    def stats(self) -> dict | None:
        try:
            return self.client.stats()
        except Exception:  # noqa: BLE001 - stats are best-effort
            return None

    def close(self) -> None:
        try:
            self.client.close()
        except Exception:  # noqa: BLE001 - teardown is best-effort
            pass
