"""tfos.cachetier — disaggregated read-through cache tier.

One byte-budgeted LRU store (:class:`~.service.CacheTier`), one TCP
daemon (:class:`~.service.CacheServer`), two client spellings
(:class:`~.service.LocalClient` / :class:`~.service.CacheClient`), and
two planes riding them: the fleet-global prefix L2 for serving
(:class:`~.prefix.PrefixL2`) and the shared columnar frame cache for
training (the ``frames`` namespace + :class:`~.frames.FrameCache`).
See docs/SERVING.md "Cache tier".
"""

from tensorflowonspark_tpu.cachetier.frames import FrameCache
from tensorflowonspark_tpu.cachetier.prefix import PrefixL2
from tensorflowonspark_tpu.cachetier.service import (
    CacheClient,
    CacheServer,
    CacheTier,
    LocalClient,
)

__all__ = [
    "CacheClient",
    "CacheServer",
    "CacheTier",
    "FrameCache",
    "LocalClient",
    "PrefixL2",
]
