"""tfos.cachetier — disaggregated read-through cache: store, daemon,
clients.

The tf.data-service result (PAPERS.md, arXiv 2101.12127) is that the
cache belongs in its own service, not in each consumer: N consumers hit
backing storage/compute ONCE instead of N times. This module is that
shape for both planes — one byte-budgeted LRU KV store
(:class:`CacheTier`) with a thin TCP daemon (:class:`CacheServer`) and
two client spellings (:class:`LocalClient` for co-resident consumers,
:class:`CacheClient` over TCP for subprocess ones). The serving plane
rides it as the fleet-global prefix L2 (``cachetier/prefix.py``); the
training plane rides it as the shared columnar frame cache (the
``frames`` namespace, read-through against the frame files on disk).

The load-bearing design rule, proven by the chaos tests: **the cache is
an optimization, never a liveness dependency.** Every client operation
is bounded-latency and failure-is-a-miss — a SIGKILL'd daemon, a
saturated socket, or an armed ``cachetier.lookup`` drop all degrade to
hit-rate zero, never to a hang or an error on the consumer's hot path.
Concretely:

- lookups carry a deadline (socket timeout); timeout/reset/refused →
  close the connection, back off (``_DOWN_BACKOFF_S``), report miss;
- fills are fire-and-forget through a bounded drop-oldest queue on a
  background filler thread — the producing thread never blocks;
- the store itself never read-blocks on backing storage for KV
  namespaces; only the ``frames`` namespace is read-through, and that
  read happens IN the service (the whole point: one pread per frame
  however many readers want it).

Keys are caller-structured strings; the prefix plane bakes
``weights_version`` and adapter into its keys (see ``prefix.py``) so
PR-15 rollout invalidation is an exact by-key drop
(:meth:`CacheTier.invalidate` with a version prefix), never a flush.
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import socket
import struct
import threading
import time
from collections import OrderedDict, deque
from typing import Any

from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.utils.failpoints import failpoint

logger = logging.getLogger(__name__)

__all__ = [
    "CacheClient",
    "CacheServer",
    "CacheTier",
    "LocalClient",
    "frame_key",
]

_LEN = struct.Struct("!I")
_MAX_HEADER = 1 << 20  # a pickled request header beyond 1 MiB is garbage
# Per-entry admission cap as a fraction of capacity: one huge blob must
# not evict the entire working set to buy a single future hit.
_MAX_ENTRY_FRACTION = 0.5
# After a transport error the client treats the service as down for this
# long: every lookup in the window is an instant miss (no connect storm,
# no per-request timeout tax while the daemon respawns).
_DOWN_BACKOFF_S = 1.0
_DEFAULT_TIMEOUT_S = 0.05
_DEFAULT_CAPACITY = 256 << 20


# -- obs ---------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: dict[str, Any] | None = None


def metrics() -> dict[str, Any]:
    """Cache-tier counters/gauges in the process-global obs registry
    (lazy: importing this module never drags in the obs package)."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from tensorflowonspark_tpu.obs.registry import default_registry

                r = default_registry()
                _metrics = {
                    "hits": r.counter(
                        "cachetier_hits_total",
                        "cache-tier lookup hits, by namespace",
                    ),
                    "misses": r.counter(
                        "cachetier_misses_total",
                        "cache-tier lookup misses, by namespace "
                        "(timeouts and dropped lookups count here)",
                    ),
                    "evictions": r.counter(
                        "cachetier_evictions_total",
                        "cache-tier LRU evictions, by namespace",
                    ),
                    "fill_bytes": r.counter(
                        "cachetier_fill_bytes_total",
                        "bytes admitted into the cache tier, by namespace",
                    ),
                    "backing_read_bytes": r.counter(
                        "cachetier_backing_read_bytes_total",
                        "bytes the tier read through to backing storage "
                        "on a frames-namespace miss",
                    ),
                    "bytes": r.gauge(
                        "cachetier_bytes",
                        "current bytes resident in the cache tier",
                    ),
                    "hit_rate": r.gauge(
                        "cachetier_hit_rate",
                        "lifetime lookup hit fraction of the cache tier",
                    ),
                }
    return _metrics


def frame_key(path: str, off: int, span: int) -> str:
    """The ``frames``-namespace key of one columnar frame. Frames are
    immutable once written (the format has no in-place rewrite), so
    (absolute path, byte offset, span) identifies the bytes forever —
    coherence is trivial by construction."""
    return f"{os.path.abspath(path)}:{int(off)}:{int(span)}"


class CacheTier:
    """Byte-budgeted LRU KV store — the one store behind every
    transport. Namespaced string keys → immutable byte blobs.

    Thread-safe: servers fan requests out across connection handler
    threads and :class:`LocalClient` calls arrive from engine scheduler
    and reader threads concurrently, so every piece of mutable state
    here is lock-guarded.
    """

    def __init__(self, capacity_bytes: int = _DEFAULT_CAPACITY):
        if capacity_bytes < 1:
            raise ValueError(f"capacity_bytes must be >= 1, got {capacity_bytes}")
        self._lock = threading.Lock()
        # insertion/refresh order IS recency: move_to_end on hit, pop
        # from the front to evict
        self._entries: OrderedDict[tuple[str, str], bytes] = OrderedDict()  # guarded-by: self._lock
        self._bytes = 0  # guarded-by: self._lock
        self._capacity_bytes = int(capacity_bytes)  # guarded-by: self._lock
        self._hits = 0  # guarded-by: self._lock
        self._misses = 0  # guarded-by: self._lock
        self._fills = 0  # guarded-by: self._lock
        self._evictions = 0  # guarded-by: self._lock
        self._backing_read_bytes = 0  # guarded-by: self._lock

    # -- core KV ------------------------------------------------------

    def lookup(self, ns: str, key: str) -> bytes | None:
        """The blob, refreshing recency — or None. A dropped
        ``cachetier.lookup`` failpoint IS a miss (never a hang)."""
        t0 = time.perf_counter()
        if failpoint("cachetier.lookup") == "drop":
            self._count_miss(ns)
            return None
        with self._lock:
            blob = self._entries.get((ns, key))
            if blob is not None:
                self._entries.move_to_end((ns, key))
                self._hits += 1
                rate = self._hits / (self._hits + self._misses)
            else:
                self._misses += 1
                rate = self._hits / (self._hits + self._misses)
        m = metrics()
        (m["hits"] if blob is not None else m["misses"]).inc(ns=ns)
        m["hit_rate"].set(rate)
        _spans().record("cachetier.lookup", time.perf_counter() - t0)
        return blob

    def fill(self, ns: str, key: str, blob: bytes) -> bool:
        """Admit one entry (idempotent — refills refresh recency and
        replace bytes). Returns False when refused: a dropped
        ``cachetier.fill`` failpoint, or a blob too large to admit
        without evicting most of the working set."""
        t0 = time.perf_counter()
        if failpoint("cachetier.fill") == "drop":
            return False
        blob = bytes(blob)
        n = len(blob)
        with self._lock:
            if n > self._capacity_bytes * _MAX_ENTRY_FRACTION:
                return False
            old = self._entries.pop((ns, key), None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[(ns, key)] = blob
            self._bytes += n
            self._fills += 1
            self._evict_locked()
            cur = self._bytes
        m = metrics()
        m["fill_bytes"].inc(n, ns=ns)
        m["bytes"].set(cur)
        _spans().record("cachetier.fill", time.perf_counter() - t0)
        return True

    def invalidate(self, ns: str, prefix: str = "") -> int:
        """Drop every ``ns`` entry whose key starts with ``prefix`` —
        the exact-by-key reclamation path (a rollout drops the old
        ``weights_version`` prefix; nothing else is touched)."""
        with self._lock:
            doomed = [
                k for k in self._entries
                if k[0] == ns and k[1].startswith(prefix)
            ]
            for k in doomed:
                self._bytes -= len(self._entries.pop(k))
            cur = self._bytes
        metrics()["bytes"].set(cur)
        return len(doomed)

    def _evict_locked(self) -> None:  # lint: holds-lock
        """LRU-evict down to budget. Caller holds ``_lock``. A dropped
        ``cachetier.evict`` failpoint ends the round — the store runs
        transiently over budget (the next fill resumes), never
        corrupts."""
        evicted = 0
        while self._bytes > self._capacity_bytes and self._entries:
            if failpoint("cachetier.evict") == "drop":
                break
            (ns, key), blob = self._entries.popitem(last=False)
            self._bytes -= len(blob)
            self._evictions += 1
            evicted += 1
            metrics()["evictions"].inc(ns=ns)
        if evicted:
            logger.debug("cachetier evicted %d entries", evicted)

    # -- frames namespace: read-through -------------------------------

    def get_frame(self, path: str, off: int, span: int) -> bytes | None:
        """One columnar frame's bytes, read-through: a miss preads the
        backing file HERE — in the service — so N readers cost one
        backing read. Returns None only when the backing read itself
        fails (caller falls back to its local path)."""
        key = frame_key(path, off, span)
        blob = self.lookup("frames", key)
        if blob is not None:
            return blob
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                blob = os.pread(fd, int(span), int(off))
            finally:
                os.close(fd)
        except OSError:
            logger.warning("cachetier backing read failed: %s", path,
                           exc_info=True)
            return None
        if len(blob) != int(span):
            logger.warning(
                "cachetier short backing read %s@%d: %d of %d bytes",
                path, off, len(blob), span,
            )
            return None
        with self._lock:
            self._backing_read_bytes += len(blob)
        metrics()["backing_read_bytes"].inc(len(blob))
        self.fill("frames", key, blob)
        return blob

    # -- knob plane ---------------------------------------------------

    @property
    def capacity_bytes(self) -> int:
        with self._lock:
            return self._capacity_bytes

    def set_capacity(self, capacity_bytes: int) -> None:
        """Resize the byte budget (the autotune actuation path —
        ``cachetier_capacity`` knob). Shrinking evicts immediately."""
        capacity_bytes = int(capacity_bytes)
        if capacity_bytes < 1:
            raise ValueError(f"capacity_bytes must be >= 1, got {capacity_bytes}")
        with self._lock:
            self._capacity_bytes = capacity_bytes
            self._evict_locked()
            cur = self._bytes
        metrics()["bytes"].set(cur)

    def _count_miss(self, ns: str) -> None:
        with self._lock:
            self._misses += 1
            rate = self._hits / (self._hits + self._misses)
        m = metrics()
        m["misses"].inc(ns=ns)
        m["hit_rate"].set(rate)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "fills": self._fills,
                "evictions": self._evictions,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacity_bytes": self._capacity_bytes,
                "backing_read_bytes": self._backing_read_bytes,
            }


def _spans():
    from tensorflowonspark_tpu.obs import spans as obs_spans

    return obs_spans.get_tracer()


# ---------------------------------------------------------------------------
# TCP daemon
# ---------------------------------------------------------------------------
#
# Framing, both directions: u32 header length, pickled wire-encoded
# header dict, then exactly header["nbytes"] raw payload bytes (lookup
# replies and fill requests; every other message has no payload). The
# header dicts go through cluster/wire.py encode/decode — the protocol
# is declared in WIRE_SCHEMAS ("cachetier.*") and gated by wirecheck.


def _send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    raw = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(raw)) + raw + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("cachetier peer closed mid-message")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> tuple[dict, bytes]:
    (hlen,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if hlen > _MAX_HEADER:
        raise ConnectionError(f"cachetier header too large ({hlen} bytes)")
    header = pickle.loads(_recv_exact(sock, hlen))
    if not isinstance(header, dict):
        raise ConnectionError("cachetier header is not a dict")
    nbytes = header.get("nbytes")
    payload = b""
    if isinstance(nbytes, int) and nbytes > 0 and wire.message_kind(header) in (
        "CFILL",
        "COK",
    ):
        payload = _recv_exact(sock, nbytes)
    return header, payload


class CacheServer:
    """The daemon: one accept loop, one handler thread per connection,
    all requests answered from a single :class:`CacheTier`. Runnable
    in-process (fleet supervision spawns it as a subprocess via
    ``python -m tensorflowonspark_tpu.cachetier.service``) and built to
    die rudely: every client treats a vanished server as a miss."""

    def __init__(self, tier: CacheTier, host: str = "127.0.0.1",
                 port: int = 0):
        self.tier = tier
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(64)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "CacheServer":
        t = threading.Thread(
            target=self._accept_loop, name="cachetier-accept", daemon=True
        )
        t.start()
        self._accept_thread = t
        return self

    def _accept_loop(self) -> None:
        self._lsock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # closed under us
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="cachetier-conn", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(30.0)
            while not self._stop.is_set():
                try:
                    header, payload = _recv_msg(conn)
                except (ConnectionError, socket.timeout, OSError,
                        pickle.UnpicklingError, EOFError):
                    return
                try:
                    reply, body = self._handle(header, payload)
                except wire.WireError:
                    logger.warning("cachetier malformed request",
                                   exc_info=True)
                    return  # protocol breach: drop the connection
                _send_msg(conn, reply, body)
        except OSError:
            pass  # client vanished mid-reply; nothing to clean up
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, header: dict, payload: bytes) -> tuple[dict, bytes]:
        kind = wire.message_kind(header)
        if kind == "CLOOKUP":
            req = wire.decode("cachetier.LOOKUP", header)
            blob = None
            path = req.get("path")
            if req["ns"] == "frames" and path:
                blob = self.tier.get_frame(
                    path, req.get("off") or 0, req.get("span") or 0
                )
            else:
                blob = self.tier.lookup(req["ns"], req["key"])
            if blob is None:
                return wire.encode(
                    "cachetier.LOOKUP.reply", hit=False, nbytes=0
                ), b""
            return wire.encode(
                "cachetier.LOOKUP.reply", hit=True, nbytes=len(blob)
            ), blob
        if kind == "CFILL":
            req = wire.decode("cachetier.FILL", header)
            stored = self.tier.fill(req["ns"], req["key"], payload)
            return wire.encode("cachetier.FILL.reply", stored=stored), b""
        if kind == "CINVAL":
            req = wire.decode("cachetier.INVALIDATE", header)
            n = self.tier.invalidate(req["ns"], req["prefix"])
            return wire.encode("cachetier.INVALIDATE.reply", dropped=n), b""
        if kind == "CSTATS":
            wire.decode("cachetier.STATS", header)
            st = self.tier.stats()
            return wire.encode(
                "cachetier.STATS.reply",
                hits=st["hits"],
                misses=st["misses"],
                fills=st["fills"],
                evictions=st["evictions"],
                entries=st["entries"],
                bytes=st["bytes"],
                capacity_bytes=st["capacity_bytes"],
                backing_read_bytes=st["backing_read_bytes"],
            ), b""
        raise wire.WireDecodeError(f"cachetier: unknown kind {kind!r}")

    def close(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# clients
# ---------------------------------------------------------------------------


class LocalClient:
    """In-process client: direct calls into a shared :class:`CacheTier`
    (the `InProcessReplica` / co-resident-reader spelling — same
    interface as :class:`CacheClient`, zero transport)."""

    def __init__(self, tier: CacheTier):
        self.tier = tier

    def lookup(self, ns: str, key: str,
               timeout_s: float = _DEFAULT_TIMEOUT_S) -> bytes | None:
        return self.tier.lookup(ns, key)

    def fill(self, ns: str, key: str, blob: bytes) -> None:
        self.tier.fill(ns, key, blob)

    def get_frame(self, path: str, off: int, span: int,
                  timeout_s: float = _DEFAULT_TIMEOUT_S) -> bytes | None:
        return self.tier.get_frame(path, off, span)

    def invalidate(self, ns: str, prefix: str = "",
                   timeout_s: float = 5.0) -> int:
        return self.tier.invalidate(ns, prefix)

    def stats(self, timeout_s: float = 5.0) -> dict | None:
        return self.tier.stats()

    def close(self) -> None:
        pass


class CacheClient:
    """TCP client with the failure-is-a-miss contract baked in.

    One connection, serialized request/reply under ``_lock`` (the
    protocol is strictly ping-pong per connection; concurrency comes
    from multiple clients, one per consumer thread pool is unnecessary
    because lookups are sub-ms and fills ride the filler thread).
    Every transport error closes the socket, arms a down-window
    (``_DOWN_BACKOFF_S`` — instant misses, no connect storm while the
    daemon respawns), and surfaces as a miss/no-op. Nothing here ever
    raises into the consumer's hot path.
    """

    def __init__(self, address: str, *, fill_queue: int = 64,
                 connect_timeout_s: float = 1.0):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self._connect_timeout_s = connect_timeout_s
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None  # guarded-by: self._lock
        self._down_until = 0.0  # guarded-by: self._lock (monotonic)
        self._closed = threading.Event()  # thread-safe; no guard needed
        # fire-and-forget fills: bounded drop-oldest queue drained by
        # one filler thread — the producing thread never blocks on the
        # network
        self._fill_q: deque[tuple[str, str, bytes]] = deque(maxlen=fill_queue)  # guarded-by: self._fill_cv
        self._fill_cv = threading.Condition()
        self._fill_dropped = 0  # guarded-by: self._fill_cv
        self._filler = threading.Thread(
            target=self._fill_loop, name="cachetier-filler", daemon=True
        )
        self._filler.start()

    # -- transport ----------------------------------------------------

    def _connect_locked(self) -> socket.socket | None:  # lint: holds-lock
        """Caller holds ``_lock``."""
        if self._sock is not None:
            return self._sock
        if self._closed.is_set() or time.monotonic() < self._down_until:
            return None
        try:
            s = socket.create_connection(
                (self.host, self.port), timeout=self._connect_timeout_s
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            self._down_until = time.monotonic() + _DOWN_BACKOFF_S
            return None
        self._sock = s
        return s

    def _drop_conn_locked(self) -> None:  # lint: holds-lock
        """Caller holds ``_lock``."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._down_until = time.monotonic() + _DOWN_BACKOFF_S

    def _roundtrip(self, header: dict, payload: bytes,
                   timeout_s: float) -> tuple[dict, bytes] | None:
        """One request/reply; None on ANY failure (that IS the miss)."""
        with self._lock:
            s = self._connect_locked()
            if s is None:
                return None
            try:
                s.settimeout(max(timeout_s, 1e-3))
                _send_msg(s, header, payload)
                return _recv_msg(s)  # lint: blocking-ok: the socket carries the caller's timeout (settimeout above) — recv is deadline-bounded, and the lock serializes the ping-pong protocol by design
            except (OSError, ConnectionError, socket.timeout,
                    pickle.UnpicklingError, EOFError):
                self._drop_conn_locked()
                return None

    # -- the client surface -------------------------------------------

    def lookup(self, ns: str, key: str,
               timeout_s: float = _DEFAULT_TIMEOUT_S) -> bytes | None:
        out = self._roundtrip(
            wire.encode("cachetier.LOOKUP", ns=ns, key=key), b"", timeout_s
        )
        if out is None:
            return None
        try:
            reply = wire.decode("cachetier.LOOKUP.reply", out[0])
        except wire.WireError:
            return None
        return out[1] if reply["hit"] else None

    def get_frame(self, path: str, off: int, span: int,
                  timeout_s: float = _DEFAULT_TIMEOUT_S) -> bytes | None:
        out = self._roundtrip(
            wire.encode(
                "cachetier.LOOKUP",
                ns="frames",
                key=frame_key(path, off, span),
                path=os.path.abspath(path),
                off=int(off),
                span=int(span),
            ),
            b"",
            timeout_s,
        )
        if out is None:
            return None
        try:
            reply = wire.decode("cachetier.LOOKUP.reply", out[0])
        except wire.WireError:
            return None
        if not reply["hit"] or len(out[1]) != int(span):
            return None
        return out[1]

    def fill(self, ns: str, key: str, blob: bytes) -> None:
        """Fire-and-forget: enqueue and return. A full queue drops the
        OLDEST pending fill (freshest data wins under pressure)."""
        with self._fill_cv:
            if len(self._fill_q) == self._fill_q.maxlen:
                self._fill_dropped += 1
            self._fill_q.append((ns, key, bytes(blob)))
            self._fill_cv.notify()

    def _fill_loop(self) -> None:
        while True:
            with self._fill_cv:
                while not self._fill_q and not self._closed.is_set():
                    self._fill_cv.wait(timeout=0.5)
                if self._closed.is_set() and not self._fill_q:
                    return
                ns, key, blob = self._fill_q.popleft()
            header = wire.encode(
                "cachetier.FILL", ns=ns, key=key, nbytes=len(blob)
            )
            # a failed fill is simply not cached; the roundtrip already
            # armed the down-window
            self._roundtrip(header, blob, timeout_s=2.0)

    def invalidate(self, ns: str, prefix: str = "",
                   timeout_s: float = 5.0) -> int:
        out = self._roundtrip(
            wire.encode("cachetier.INVALIDATE", ns=ns, prefix=prefix),
            b"", timeout_s,
        )
        if out is None:
            return 0
        try:
            return wire.decode("cachetier.INVALIDATE.reply", out[0])["dropped"]
        except wire.WireError:
            return 0

    def stats(self, timeout_s: float = 5.0) -> dict | None:
        out = self._roundtrip(wire.encode("cachetier.STATS"), b"", timeout_s)
        if out is None:
            return None
        try:
            return wire.decode("cachetier.STATS.reply", out[0])
        except wire.WireError:
            return None

    def pending_fills(self) -> int:
        with self._fill_cv:
            return len(self._fill_q)

    def close(self) -> None:
        self._closed.set()
        with self._fill_cv:
            self._fill_cv.notify_all()
        self._filler.join(timeout=2.0)
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


# ---------------------------------------------------------------------------
# standalone daemon entry (the fleet's spawn target; SIGKILL-able)
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="tfos cachetier daemon")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--port-file", default=None,
                    help="write the bound port here once listening "
                    "(the spawn barrier)")
    ap.add_argument("--capacity-bytes", type=int, default=_DEFAULT_CAPACITY)
    args = ap.parse_args(argv)
    server = CacheServer(
        CacheTier(capacity_bytes=args.capacity_bytes),
        host=args.host, port=args.port,
    ).start()
    logger.info("cachetier daemon listening on %s", server.address)
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(server.port))
        os.replace(tmp, args.port_file)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
