"""Fleet-global prefix L2 — the serving plane's cachetier client.

The per-engine ``_PrefixStore`` (serving/engine.py) stays L1: device-
resident, scheduler-thread-only, zero-copy hits. This module is the L2
behind it — a :class:`PrefixL2` wraps a cachetier client (in-process
``LocalClient`` for `InProcessReplica`s, TCP ``CacheClient`` for
subprocess ones) so a prefix prefilled by ANY replica is reusable by
all of them. At fleet scale the shared system-prompt prefix is the
single largest recoverable compute saving; before this tier, router
prefix-affinity was a correctness-shaped crutch papering over the
re-prefill (it now demotes to a locality hint — serving/router.py).

Keying — the exactness contract::

    prefix|<weights_version>|<adapter>|<t0,t1,...,tk>

``weights_version`` and adapter are baked into every key, so a PR-15
rollout invalidates EXACTLY (drop the old version's key prefix, touch
nothing else) and a stale-version cache can never extend a new-version
decode: the new version's lookups simply never construct the old keys.

Latency contract (the cache is never a liveness dependency):

- :meth:`lookup` runs on the engine scheduler thread, so it carries a
  TOTAL deadline across its depth probes (miss-on-timeout, default
  50 ms) and never raises;
- :meth:`offer` is fire-and-forget: the scheduler thread enqueues the
  device-array leaves and returns; a background filler thread pays the
  device→host transfer + pickle + transport, with a bounded drop-oldest
  queue so a slow or dead service sheds offers instead of backpressure.

Values are pickled lists of contiguous numpy arrays — a bit-exact
round-trip of the single-row KV cache leaves (the engine owns the
treedef; see ``ContinuousBatcher._l2_reconstruct``).
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Sequence

logger = logging.getLogger(__name__)

__all__ = ["PrefixL2", "prefix_key", "version_prefix"]

NS = "prefix"


def prefix_key(version: Any, adapter: str | None, tokens: Sequence[int]) -> str:
    """The L2 key of one ``(weights_version, adapter, token-prefix)``."""
    toks = ",".join(str(int(t)) for t in tokens)
    return f"{version}|{adapter or ''}|{toks}"


def version_prefix(version: Any) -> str:
    """The key prefix owned by one weights version — the argument a
    rollout passes to ``invalidate`` to reclaim that version exactly."""
    return f"{version}|"


class PrefixL2:
    """The engine-facing L2 facade over a cachetier client."""

    def __init__(
        self,
        client: Any,
        *,
        chunk: int,
        lookup_timeout_s: float = 0.05,
        fill_queue: int = 32,
        dedup_window: int = 256,
        own_client: bool = False,
    ):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.client = client
        self._own_client = bool(own_client)
        self.chunk = int(chunk)
        self.lookup_timeout_s = float(lookup_timeout_s)
        self._lock = threading.Lock()
        self._hits = 0  # guarded-by: self._lock
        self._misses = 0  # guarded-by: self._lock
        self._offered = 0  # guarded-by: self._cv
        self._offer_drops = 0  # guarded-by: self._cv
        self._offer_dedups = 0  # guarded-by: self._cv
        self._closed = False  # guarded-by: self._cv
        # Offer dedup: a key's value is deterministic (the KV cache is
        # a pure function of (version, adapter, tokens) — the version
        # is IN the key), so re-publishing a recently-offered key buys
        # nothing and costs a device→host copy + pickle per repeat —
        # on a saturated host that transfer tax is the difference
        # between the L2 paying for itself and not. Bounded window, and
        # self-healing: a lookup MISS on a key evicts it here (see
        # lookup), so an entry the tier dropped (LRU pressure, daemon
        # respawn) is re-offered the next time any request completes it.
        self._recent: "OrderedDict[str, None]" = OrderedDict()  # guarded-by: self._cv
        self._dedup_window = max(0, int(dedup_window))
        # fire-and-forget offers: the scheduler thread appends leaves
        # (no transfer, no pickle) and the filler thread pays the rest
        self._q: deque[tuple[str, list]] = deque(maxlen=fill_queue)  # guarded-by: self._cv
        self._cv = threading.Condition()
        self._filler = threading.Thread(
            target=self._fill_loop, name="prefix-l2-filler", daemon=True
        )
        self._filler.start()

    # -- lookup (scheduler thread; bounded, never raises) --------------

    def _depths(self, n: int) -> list[int]:
        """Candidate stored depths for an ``n``-token prompt, longest
        first: the full prompt plus the L1 boundary-insert ladder
        (``chunk * 2**k``) — exactly the depths any engine inserts at,
        so probing anything else would be wasted roundtrips."""
        out = {n}
        d = self.chunk
        while d < n:
            out.add(d)
            d *= 2
        return sorted(out, reverse=True)

    def lookup(
        self, tokens: Sequence[int], adapter: str | None, version: Any
    ) -> tuple[list, int] | None:
        """Longest cached prefix of ``tokens`` under this version —
        ``(numpy leaves, depth)`` — or None. Spends at most
        ``lookup_timeout_s`` across ALL depth probes; a slow or dead
        service is a miss, never a stall."""
        n = len(tokens)
        if n < 2:
            return None
        deadline = time.monotonic() + self.lookup_timeout_s
        try:
            for depth in self._depths(n):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                key = prefix_key(version, adapter, tokens[:depth])
                blob = self.client.lookup(NS, key, timeout_s=remaining)
                if blob is None:
                    # the tier does not have this key — clear it from
                    # the offer-dedup window so the next engine that
                    # completes this prefix re-publishes it (the self-
                    # heal that makes dedup safe under LRU eviction
                    # and daemon respawn)
                    with self._cv:
                        self._recent.pop(key, None)
                    continue
                leaves = pickle.loads(blob)
                if not isinstance(leaves, list):
                    continue
                with self._lock:
                    self._hits += 1
                return leaves, depth
        except Exception:  # noqa: BLE001 - L2 failure IS a miss
            logger.warning("prefix L2 lookup failed", exc_info=True)
        with self._lock:
            self._misses += 1
        return None

    # -- offer (scheduler thread enqueues; filler thread pays) ---------

    def offer(
        self,
        tokens: Sequence[int],
        leaves: list,
        adapter: str | None,
        version: Any,
    ) -> None:
        """Publish one prefix's cache leaves, fire-and-forget. ``leaves``
        are the flattened single-row cache arrays (device or host); the
        device→host transfer happens on the filler thread, never
        here."""
        key = prefix_key(version, adapter, tokens)
        with self._cv:
            if self._closed:
                return
            if self._dedup_window:
                if key in self._recent:
                    self._recent.move_to_end(key)
                    self._offer_dedups += 1
                    return
                self._recent[key] = None
                while len(self._recent) > self._dedup_window:
                    self._recent.popitem(last=False)
            if len(self._q) == self._q.maxlen:
                self._offer_drops += 1
            self._q.append((key, list(leaves)))
            self._offered += 1
            self._cv.notify()

    def _fill_loop(self) -> None:
        import numpy as np

        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(timeout=0.5)
                if self._closed and not self._q:
                    return
                key, leaves = self._q.popleft()
            try:
                # jax arrays are immutable, so reading them from this
                # thread is safe; np.asarray is the device→host sync
                host = [np.ascontiguousarray(np.asarray(x)) for x in leaves]
                blob = pickle.dumps(host, protocol=pickle.HIGHEST_PROTOCOL)
                self.client.fill(NS, key, blob)
            except Exception:  # noqa: BLE001 - a lost offer is a miss later
                logger.warning("prefix L2 offer failed", exc_info=True)

    # -- maintenance ---------------------------------------------------

    def invalidate_version(self, version: Any) -> int:
        """Exact-by-key reclamation of one weights version (the rollout
        hook); returns entries dropped (0 when the service is down —
        harmless: the old version's keys can never be looked up again)."""
        try:
            return self.client.invalidate(NS, version_prefix(version))
        except Exception:  # noqa: BLE001 - reclamation is best-effort
            logger.warning("prefix L2 invalidate failed", exc_info=True)
            return 0

    def stats(self) -> dict[str, int]:
        with self._lock:
            hits, misses = self._hits, self._misses
        with self._cv:
            offered, drops = self._offered, self._offer_drops
            dedups = self._offer_dedups
        return {
            "l2_hits": hits,
            "l2_misses": misses,
            "l2_offered": offered,
            "l2_offer_drops": drops,
            "l2_offer_dedups": dedups,
        }

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._filler.join(timeout=2.0)
        if self._own_client:
            try:
                self.client.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                logger.warning("prefix L2 client close failed",
                               exc_info=True)
