"""Spark-Streaming-shaped micro-batch streaming (DStream object model).

Reference parity: ``TFCluster.train`` accepted a ``DStream`` and fed each
arriving RDD via ``foreachRDD`` (``TFCluster.py:train``, SURVEY.md §3.2),
and ``TFCluster.shutdown(ssc, ...)`` awaited streaming termination. The
reference delegated the object model to pyspark; this module provides the
TPU-native equivalent: a :class:`StreamingContext` scheduler thread turns
sources into micro-batch "RDDs" (lists of partitions) on a fixed
interval, :class:`DStream` carries the record-level transformation chain,
and ``foreachRDD`` delivers to output callbacks — e.g. the bridge
``TFCluster.train`` installs to feed workers through the data plane.

Sources mirror the pyspark ones the reference's examples used:
``textFileStream`` (watch a directory, one partition per new file —
the HDFS-dir pattern of ``examples/mnist`` streaming), ``queueStream``
(pre-staged RDDs), and ``generatorStream`` (callable per tick; the
escape hatch for custom receivers).

Usage::

    ssc = StreamingContext(batch_interval=1.0)
    stream = ssc.textFileStream("/data/incoming").map(parse_line)
    cluster.train(stream)          # registers the feed bridge
    ssc.start()
    ...
    cluster.shutdown(ssc=ssc)      # stop stream, drain, tear down
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Iterable, Sequence

logger = logging.getLogger(__name__)

# An "RDD" in this model: a list of partitions, each a list of records.
RDD = list

# textFileStream settle: an mtime this much in the past is trusted to
# mean "the writer is done" even on coarse-granularity filesystems
# (ext3/exFAT/network mounts report 1-2 s resolution, so a fresher
# "old-looking" mtime could belong to an actively-growing file).
_MTIME_TRUST_NS = 2_000_000_000


class DStream:
    """A discretized stream: per-tick RDDs flowing through a
    transformation chain. Transformations return new DStreams; output
    operations (:meth:`foreachRDD`) register callbacks on the context."""

    def __init__(self, ssc: "StreamingContext", parent: "DStream | None",
                 op: Callable[..., RDD] | None,
                 parent2: "DStream | None" = None):
        self._ssc = ssc
        self._parent = parent
        self._parent2 = parent2  # set for two-input ops (union)
        self._op = op

    # -- transformations (record level) --------------------------------
    def map(self, fn: Callable[[Any], Any]) -> "DStream":
        return self._derive(
            lambda rdd: [[fn(r) for r in part] for part in rdd]
        )

    def filter(self, fn: Callable[[Any], bool]) -> "DStream":
        return self._derive(
            lambda rdd: [[r for r in part if fn(r)] for part in rdd]
        )

    def flatMap(self, fn: Callable[[Any], Iterable[Any]]) -> "DStream":
        return self._derive(
            lambda rdd: [
                [x for r in part for x in fn(r)] for part in rdd
            ]
        )

    def mapPartitions(
        self, fn: Callable[[Iterable[Any]], Iterable[Any]]
    ) -> "DStream":
        return self._derive(lambda rdd: [list(fn(iter(p))) for p in rdd])

    def repartition(self, n: int) -> "DStream":
        def op(rdd: RDD) -> RDD:
            records = [r for part in rdd for r in part]
            k = max(1, n)
            size = -(-len(records) // k) if records else 0
            return [
                records[i * size : (i + 1) * size] for i in range(k)
            ] if size else [[] for _ in range(k)]

        return self._derive(op)

    def _derive(self, op: Callable[..., RDD]) -> "DStream":
        return DStream(self._ssc, self, op)

    # -- windowed transformations (micro-batch level) -------------------
    #
    # Windows are counted in MICRO-BATCHES, not seconds (a discretized
    # stream's natural unit; pyspark's windowDuration/batch_interval
    # ratio). A window advances once per scheduler tick on which its
    # source produced a micro-batch — empty ticks (source returned None)
    # do not slide the window. Window state lives in the op closure; the
    # per-tick node memo in :meth:`_materialize` guarantees exactly one
    # advance per tick however many outputs share the windowed node.

    def window(self, num_batches: int) -> "DStream":
        """Union of the last ``num_batches`` micro-batches."""
        if num_batches < 1:
            raise ValueError("window needs num_batches >= 1")
        import collections

        buf: collections.deque[RDD] = collections.deque(maxlen=num_batches)

        def op(rdd: RDD) -> RDD:
            buf.append(rdd)
            return [part for r in buf for part in r]

        return self._derive(op)

    def countByWindow(self, num_batches: int) -> "DStream":
        """Record count over the window: one single-record partition."""
        return self.window(num_batches).count()

    def reduceByWindow(
        self, fn: Callable[[Any, Any], Any], num_batches: int
    ) -> "DStream":
        """Fold all records in the window with ``fn``; empty window ->
        empty micro-batch."""
        import functools

        def reduce_op(rdd: RDD) -> RDD:
            records = [r for part in rdd for r in part]
            return [[functools.reduce(fn, records)]] if records else [[]]

        return self.window(num_batches)._derive(reduce_op)

    def count(self) -> "DStream":
        """Per-micro-batch record count (pyspark ``DStream.count``)."""
        return self._derive(lambda rdd: [[sum(len(p) for p in rdd)]])

    def union(self, other: "DStream") -> "DStream":
        """Merge two streams derived from the same source (their per-tick
        partitions are concatenated)."""
        if other._ssc is not self._ssc:
            raise ValueError("union across StreamingContexts")
        if other._source() is not self._source():
            raise ValueError(
                "union requires streams derived from the same source "
                "(cross-source joins are not part of the feed model)"
            )
        return DStream(
            self._ssc, self, lambda a, b: list(a) + list(b), parent2=other
        )

    # -- output --------------------------------------------------------
    def foreachRDD(self, fn: Callable[[RDD], None]) -> None:
        """Register ``fn`` to run on each materialized micro-batch."""
        self._ssc._register_output(self, fn)

    def pprint(self, num: int = 10) -> None:
        """Print the first ``num`` records of each micro-batch (pyspark
        ``DStream.pprint``): a timestamp header, records, a truncation
        marker — the debugging output op."""

        def show(rdd: RDD) -> None:
            records = [r for part in rdd for r in part]
            print(f"-------- micro-batch @ {time.strftime('%X')} --------")
            for r in records[:num]:
                print(r)
            if len(records) > num:
                print(f"... ({len(records) - num} more)")

        self.foreachRDD(show)

    def saveAsTextFiles(self, prefix: str, suffix: str = "") -> None:
        """Write each micro-batch as a directory of part files (pyspark
        ``DStream.saveAsTextFiles``): ``<prefix>-<epoch_ms>[.suffix]/
        part-NNNNN``, one part per partition, one ``str(record)`` per
        line. Timestamp naming never collides across job restarts
        (pyspark's convention), and each batch dir is written under a
        dot-prefixed temp name then renamed, so directory watchers
        (e.g. ``textFileStream`` on the parent) never observe a
        half-written batch."""

        def save(rdd: RDD) -> None:
            stamp = int(time.time() * 1000)
            while True:
                d = f"{prefix}-{stamp}"
                if suffix:
                    d = f"{d}.{suffix}"
                parent, base = os.path.split(d)
                tmp = os.path.join(parent or ".", f".{base}.tmp")
                # Bump past BOTH an in-flight temp dir and an already-
                # materialized destination (e.g. a prior run's output with
                # a colliding ms stamp) — otherwise the final os.rename
                # raises inside the scheduler thread.
                if os.path.exists(d):
                    stamp += 1
                    continue
                try:
                    os.makedirs(tmp, exist_ok=False)
                    break
                except FileExistsError:
                    stamp += 1  # two ticks in one ms; bump
            for i, part in enumerate(rdd):
                with open(os.path.join(tmp, f"part-{i:05d}"), "w") as f:
                    for r in part:
                        f.write(f"{r}\n")
            # Atomic materialization. The destination can still have
            # materialized since the pre-check above (a concurrent job
            # with the same prefix writing during our part-file loop),
            # so retry the rename under a fresh stamp instead of raising
            # in the scheduler thread. Bounded: a persistent non-
            # collision error (EACCES, missing parent) must surface.
            for _ in range(100):
                try:
                    os.rename(tmp, d)
                    break
                except OSError:
                    if not os.path.exists(d):
                        raise  # not a collision — a real filesystem error
                    stamp += 1
                    d = f"{prefix}-{stamp}"
                    if suffix:
                        d = f"{d}.{suffix}"
            else:
                raise OSError(
                    f"saveAsTextFiles could not materialize a batch dir "
                    f"for prefix {prefix!r} after 100 stamp bumps"
                )

        self.foreachRDD(save)

    # -- evaluation ----------------------------------------------------
    def _materialize(
        self, source_rdd: RDD, memo: dict[int, RDD] | None = None
    ) -> RDD:
        """Evaluate this node for one tick. ``memo`` (id(node) -> RDD)
        makes every node evaluate at most once per tick — required for
        correctness of stateful window ops shared by several outputs.
        Iterative post-order walk: arbitrarily long transformation
        chains must not hit the Python recursion limit."""
        if memo is None:
            memo = {}
        if self._op is None:
            return source_rdd

        def value_of(node: "DStream") -> RDD:
            return source_rdd if node._op is None else memo[id(node)]

        stack: list[DStream] = [self]
        while stack:
            node = stack[-1]
            if node._op is None or id(node) in memo:
                stack.pop()
                continue
            pending = [
                p
                for p in (node._parent, node._parent2)
                if p is not None and p._op is not None and id(p) not in memo
            ]
            if pending:
                stack.extend(pending)
                continue
            stack.pop()
            if node._parent2 is not None:
                memo[id(node)] = node._op(
                    value_of(node._parent), value_of(node._parent2)
                )
            else:
                memo[id(node)] = node._op(value_of(node._parent))
        return memo[id(self)]

    def _source(self) -> "DStream":
        node = self
        while node._parent is not None:
            node = node._parent
        return node


class StreamingContext:
    """Scheduler for DStreams: ticks every ``batch_interval`` seconds,
    materializes each source's new micro-batch, and runs output ops.

    Errors raised by sources, transformations, or outputs stop the
    context and re-raise from :meth:`awaitTermination` (the reference's
    behavior: a failing foreachRDD killed the streaming job)."""

    def __init__(self, batch_interval: float = 1.0):
        self.batch_interval = float(batch_interval)
        self._sources: list[tuple[DStream, Callable[[], RDD | None]]] = []
        self._outputs: list[tuple[DStream, Callable[[RDD], None]]] = []
        self._thread: threading.Thread | None = None
        self._stopped = threading.Event()
        self._terminated = threading.Event()
        self._error: BaseException | None = None
        self._started = False

    # -- sources -------------------------------------------------------
    def queueStream(
        self,
        rdds: Sequence[Iterable] | Any,
        one_at_a_time: bool = True,
        default: RDD | None = None,
    ) -> DStream:
        """Stream from a pre-staged sequence (or ``queue.Queue``) of RDDs.

        ``one_at_a_time=False`` drains everything available each tick
        into one micro-batch, like pyspark's queueStream."""
        import queue as stdqueue

        if not isinstance(rdds, stdqueue.Queue):
            q: stdqueue.Queue = stdqueue.Queue()
            for rdd in rdds:
                q.put(rdd)
            rdds = q

        def poll() -> RDD | None:
            batches: list[RDD] = []
            try:
                while True:
                    batches.append(_as_rdd(rdds.get_nowait()))
                    if one_at_a_time:
                        break
            except stdqueue.Empty:
                pass
            if not batches:
                return default
            if len(batches) == 1:
                return batches[0]
            return [part for rdd in batches for part in rdd]

        return self._add_source(poll)

    def textFileStream(self, directory: str) -> DStream:
        """Watch ``directory``; each tick emits newly appeared files as
        one partition of text lines per file (the reference examples'
        HDFS-directory streaming pattern)."""
        seen: set[str] = set()
        # A freshly listed file may still be mid-write; reading it
        # immediately would deliver it truncated AND mark it seen —
        # silently dropping the tail. Two settle rules, either suffices:
        #
        # 1. First-sighting by age: mtime at least one batch_interval old
        #    AND older than _MTIME_TRUST_NS. The trust floor matters on
        #    coarse-mtime filesystems (1-2 s granularity on ext3/exFAT/
        #    some network mounts): a sub-second interval alone would read
        #    an actively-growing file whose truncated mtime merely LOOKS
        #    old. An atomically renamed-in file (the airtight producer
        #    pattern — dot-prefixed temp name then rename, like
        #    saveAsTextFiles) whose writes finished more than ~2 s ago is
        #    delivered on the FIRST tick that sees it.
        # 2. Two-tick signature: (size, mtime_ns) unchanged across
        #    consecutive ticks AND mtime one interval old — catches fresh
        #    files without waiting for the trust floor.
        #
        # A writer that stalls longer than a tick mid-write can still
        # race any polling watcher; only the rename pattern is airtight.
        pending: dict[str, tuple[int, int]] = {}

        def poll() -> RDD | None:
            try:
                names = sorted(os.listdir(directory))
            except FileNotFoundError:
                return None
            now_ns = time.time_ns()
            settle_ns = int(self.batch_interval * 1e9)
            parts: RDD = []
            for name in names:
                if name in seen or name.startswith("."):
                    continue
                path = os.path.join(directory, name)
                try:
                    st = os.stat(path)
                except OSError:
                    pending.pop(name, None)
                    continue
                if not os.path.isfile(path):
                    seen.add(name)
                    continue
                age_ns = now_ns - st.st_mtime_ns
                sig = (st.st_size, st.st_mtime_ns)
                settled = age_ns >= max(settle_ns, _MTIME_TRUST_NS) or (
                    pending.get(name) == sig and age_ns >= settle_ns
                )
                if not settled:
                    pending[name] = sig
                    continue
                try:
                    with open(path) as f:
                        lines = [line.rstrip("\n") for line in f]
                except OSError:
                    # Deleted/renamed between stat and open: a poll
                    # exception would kill the whole scheduler, and
                    # marking it seen would drop it if it reappears.
                    pending.pop(name, None)
                    continue
                seen.add(name)
                pending.pop(name, None)
                parts.append(lines)
            return parts or None

        return self._add_source(poll)

    def generatorStream(self, fn: Callable[[], RDD | None]) -> DStream:
        """Custom receiver: ``fn()`` is called every tick and returns the
        micro-batch's partitions (or None for an empty tick)."""
        return self._add_source(lambda: _maybe_rdd(fn()))

    def _add_source(self, poll: Callable[[], RDD | None]) -> DStream:
        ds = DStream(self, None, None)
        self._sources.append((ds, poll))
        return ds

    def _register_output(
        self, ds: DStream, fn: Callable[[RDD], None]
    ) -> None:
        self._outputs.append((ds, fn))

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._started:
            raise RuntimeError("StreamingContext already started")
        if not self._outputs:
            raise RuntimeError(
                "no output operations registered (call foreachRDD, or "
                "pass the stream to TFCluster.train, before start())"
            )
        self._started = True
        self._thread = threading.Thread(
            target=self._run, name="dstream-scheduler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            while not self._stopped.is_set():
                tick_start = time.monotonic()
                for src_ds, poll in self._sources:
                    rdd = poll()
                    if rdd is None:
                        continue
                    # One shared per-tick memo: every node (not just each
                    # leaf) evaluates once, so outputs sharing ancestors
                    # reuse work and stateful window ops advance exactly
                    # once per tick.
                    memo: dict[int, RDD] = {}
                    for out_ds, fn in self._outputs:
                        if out_ds._source() is src_ds:
                            fn(out_ds._materialize(rdd, memo))
                # fixed-rate schedule, like Spark's batch interval
                elapsed = time.monotonic() - tick_start
                self._stopped.wait(max(0.0, self.batch_interval - elapsed))
        except BaseException as e:  # noqa: BLE001 - ferried to awaiter
            self._error = e
            logger.exception("streaming scheduler failed")
        finally:
            self._terminated.set()

    def stop(self, stop_grace_fully: bool = True) -> None:
        """Stop ticking. With ``stop_grace_fully`` the current tick
        finishes (the scheduler thread is joined either way). If a
        bounded non-graceful join times out, the context is NOT marked
        terminated — the scheduler's own exit does that, so
        :meth:`awaitTermination` never reports a still-running thread."""
        self._stopped.set()
        if self._thread is None:
            self._terminated.set()
            return
        self._thread.join(timeout=None if stop_grace_fully else 5.0)
        # _terminated is set by the scheduler's finally on actual exit

    def awaitTermination(self, timeout: float | None = None) -> bool:
        """Block until stopped (or ``timeout`` seconds); re-raises a
        scheduler error. Returns True if terminated."""
        done = self._terminated.wait(timeout)
        if self._error is not None:
            raise self._error
        return done

    def awaitTerminationOrTimeout(self, timeout: float) -> bool:
        return self.awaitTermination(timeout)


def _as_rdd(obj: Any) -> RDD:
    """Coerce an iterable-of-partitions or flat record list into an RDD."""
    items = list(obj)
    if items and all(
        isinstance(p, (list, tuple)) and not _is_record(p) for p in items
    ):
        return [list(p) for p in items]
    return [items]


def _is_record(p: Any) -> bool:
    # tuples are records (the framework's record convention); lists of
    # scalars are partitions
    return isinstance(p, tuple)


def _maybe_rdd(obj: Any) -> RDD | None:
    return None if obj is None else _as_rdd(obj)
