"""Int8 weight-only quantization for inference (decode is HBM-bound).

KV-cache decode reads every weight once per generated token, so the
resident weight bytes ARE the decode cost floor (BASELINE.md measures
llama1b decode at ~62% of HBM bandwidth). Per-output-channel symmetric
int8 storage halves that footprint: a 7B model's weights drop from
~13 GB bf16 to ~6.7 GB — the difference between fitting and not fitting
a 16 GB chip next to its KV cache.

Two layers:

- :func:`quantize_tree` / :func:`dequantize_tree` — pytree-level
  quantization. ``QuantTensor`` is a registered pytree node, so
  quantized trees ride jit/device_put/orbax like any param tree.
- :func:`quantized_dot` — ``x @ w`` against a ``QuantTensor`` with the
  scales applied to the fp32 accumulator per output channel: no bf16
  weight is ever materialized, so both the footprint AND the per-token
  weight read are int8. The Llama modules consume ``QuantTensor``
  kernels natively through this op (``models/llama.py:QDense``, the
  embed gather, and the head projection) — pass a ``quantize_tree``'d
  param tree to ``generate`` and decode runs against int8 weights.

Accuracy: per-channel symmetric int8 on transformer matmul weights is
the standard weight-only recipe (~0.1% relative error per layer; see
the round-trip test tolerances in ``tests/test_quant.py``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class QuantTensor:
    """Symmetric per-channel int8 weight: ``w ≈ q * scale``.

    ``q`` is int8 with the original shape; ``scale`` is fp32 broadcast
    along ``axis`` (kept as a struct field so the pair travels as one
    pytree node through jit, device placement, and checkpointing).
    """

    q: jax.Array
    scale: jax.Array
    axis: int = struct.field(pytree_node=False, default=-1)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.scale.dtype


def quantize(w: jax.Array, axis: int = -1) -> QuantTensor:
    """Per-channel symmetric int8: one scale per slice along ``axis``
    (the output-channel dim for row-major ``(in, out)`` kernels), i.e.
    the max-abs reduction runs over every OTHER axis."""
    w32 = w.astype(jnp.float32)
    channel = axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != channel)
    amax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantTensor(q=q, scale=scale, axis=channel)


def dequantize(t: QuantTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (t.q.astype(jnp.float32) * t.scale).astype(dtype)


def quantize_tree(
    params: Any,
    min_size: int = 1 << 16,
    axis: int = -1,
    axis_overrides: dict[str, int] | None = None,
) -> Any:
    """Quantize every 2-D floating leaf with ``>= min_size`` elements;
    small leaves (norm scales, biases) stay as-is. Only matrices: that is
    what the consumers handle (``QDense``, the embed gather, the head
    projection) — 3-D MoE expert banks are deliberately left unquantized
    (``parallel/moe.py`` consumes plain arrays).

    ``axis_overrides`` maps a leaf's *name* (its last pytree path key)
    to a quantization axis. The default ``{"embed": 0}`` stores the
    ``(vocab, hidden)`` embedding table with per-ROW scales: an axis=-1
    scale would be a max-abs over the whole 32k-row vocab per hidden
    unit, so a single outlier token row inflates quantization error for
    every token. The head projection keeps axis=-1 (its name is
    ``lm_head``), matching ``quantized_dot``'s output-channel contract.
    """
    if axis_overrides is None:
        axis_overrides = {"embed": 0}

    def leaf_name(path) -> str:
        if not path:
            return ""
        last = path[-1]
        for attr in ("key", "name", "idx"):
            if hasattr(last, attr):
                return str(getattr(last, attr))
        return str(last)

    def rule(path, x):
        if (
            hasattr(x, "ndim")
            and x.ndim == 2
            and x.size >= min_size
            and jnp.issubdtype(x.dtype, jnp.floating)
        ):
            return quantize(x, axis=axis_overrides.get(leaf_name(path), axis))
        return x

    return jax.tree_util.tree_map_with_path(rule, params)


def dequantize_tree(params: Any, dtype=jnp.bfloat16) -> Any:
    """Inverse of :func:`quantize_tree`; call INSIDE jit so int8 stays
    the at-rest representation."""
    return jax.tree.map(
        lambda x: dequantize(x, dtype) if isinstance(x, QuantTensor) else x,
        params,
        is_leaf=lambda x: isinstance(x, QuantTensor),
    )


def quantized_dot(x: jax.Array, w: QuantTensor) -> jax.Array:
    """``x @ w`` with the scales folded into the fp32 accumulator.

    The int8 operand feeds the dot directly (no materialized bf16
    weight); per-output-channel scales multiply the accumulator. Only
    ``axis=-1`` (output-channel) quantization is supported — that is
    what :func:`quantize_tree` produces for ``(in, out)`` kernels.
    """
    if w.axis != -1 and w.axis != w.q.ndim - 1:
        raise ValueError("quantized_dot needs output-channel (axis=-1) scales")
    acc = jax.lax.dot_general(
        x.astype(jnp.bfloat16),
        w.q,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (acc * w.scale.reshape((1,) * (acc.ndim - 1) + (-1,))).astype(
        x.dtype
    )
