"""LoRA — low-rank adapters for parameter-efficient fine-tuning.

Full fine-tuning of a 7B model needs ~3 copies of every weight in HBM
(params + grads + Adam moments, ~80 GB fp32); LoRA trains only a pair
of rank-r factors per targeted matrix (``w ≈ w_base + a @ b · s``),
shrinking trainable state to well under 1% while the frozen base stays
a single read-only copy. The TPU shape of the idea:

- :class:`LoraTensor` is a registered pytree node (like
  ``quant.QuantTensor``), so LoRA-ified param trees ride jit,
  ``device_put``, mesh sharding, and orbax unchanged.
- The base matrix is wrapped in ``stop_gradient`` INSIDE the op, so XLA
  never builds the base-weight gradient matmuls — the backward pass
  costs scale with the adapters, not the model.
- :func:`lora_optimizer` masks the frozen leaves out of the optimizer
  with ``optax.multi_transform``, so Adam moments exist ONLY for the
  adapters — that is where the HBM win comes from.
- ``models/llama.py:QDense`` consumes ``LoraTensor`` kernels natively;
  ``llama_param_shardings`` shards ``base`` like the kernel it wraps
  and the factors along their matching halves, so FSDP/TP configs work
  untouched.

Reference parity note: the reference delegated all training machinery
to TF and had no parameter-efficient path (SURVEY.md §2.3); this is
capability beyond it, motivated by the same HBM arithmetic as
BASELINE.md's optimizer-state study.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import struct

DEFAULT_TARGETS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)


@struct.dataclass
class LoraTensor:
    """``w_eff = base + a @ b * scale`` with ``base`` frozen.

    ``base`` (in, out); ``a`` (in, r) gaussian-init; ``b`` (r, out)
    zero-init — so a freshly added adapter is an exact no-op (the
    standard LoRA init). ``scale`` = alpha / r, static.
    """

    base: jax.Array
    a: jax.Array
    b: jax.Array
    scale: float = struct.field(pytree_node=False, default=1.0)

    @property
    def shape(self):
        return self.base.shape

    @property
    def dtype(self):
        return self.base.dtype


def lora_apply(x: jax.Array, w: LoraTensor) -> jax.Array:
    """``x @ w_eff`` without materializing the merged matrix: the
    adapter path is two skinny matmuls (B·S·in·r + B·S·r·out FLOPs —
    negligible at r≪min(in,out)). ``stop_gradient`` on the base keeps
    the backward pass adapter-sized."""
    base = jax.lax.stop_gradient(w.base)
    y = x @ base.astype(x.dtype)
    lo = (x @ w.a.astype(x.dtype)) @ w.b.astype(x.dtype)
    return y + lo * w.scale


def add_lora(
    params: Any,
    rank: int,
    rng: jax.Array,
    targets: Sequence[str] = DEFAULT_TARGETS,
    alpha: float | None = None,
    dtype=jnp.float32,
) -> Any:
    """Wrap every 2-D leaf whose path contains a target name in a
    :class:`LoraTensor`. ``alpha`` defaults to ``rank`` (scale 1.0).
    The wrapped tree's forward output is EXACTLY the base tree's until
    the adapters train (b starts at zero)."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    scale = (alpha if alpha is not None else float(rank)) / float(rank)
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    keys = jax.random.split(rng, len(flat))

    def name_of(path) -> str:
        return "/".join(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path
        )

    out = []
    n_wrapped = 0
    for (path, leaf), key in zip(flat, keys):
        joined = name_of(path)
        if (
            hasattr(leaf, "ndim")
            and leaf.ndim == 2
            and any(t in joined for t in targets)
        ):
            d_in, d_out = leaf.shape
            if rank > min(d_in, d_out):
                raise ValueError(
                    f"rank {rank} exceeds min dim of {joined} {leaf.shape}"
                )
            a = (
                jax.random.normal(key, (d_in, rank), dtype)
                / jnp.sqrt(jnp.asarray(d_in, dtype))
            )
            b = jnp.zeros((rank, d_out), dtype)
            out.append(LoraTensor(base=leaf, a=a, b=b, scale=scale))
            n_wrapped += 1
        else:
            out.append(leaf)
    if n_wrapped == 0:
        raise ValueError(
            f"no 2-D params matched targets {tuple(targets)}; nothing to "
            "adapt"
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def merge_lora(params: Any) -> Any:
    """Fold trained adapters into plain kernels (``base + a@b·s``) for
    serving/export — zero inference overhead, and the merged tree is a
    drop-in for every consumer of the original params."""

    def rule(x):
        if isinstance(x, LoraTensor):
            merged = (
                x.base.astype(jnp.float32)
                + (x.a.astype(jnp.float32) @ x.b.astype(jnp.float32))
                * x.scale
            )
            return merged.astype(x.base.dtype)
        return x

    return jax.tree.map(
        rule, params, is_leaf=lambda x: isinstance(x, LoraTensor)
    )


def lora_labels(params: Any) -> Any:
    """'train' / 'freeze' label tree for ``optax.multi_transform``:
    adapter factors train, everything else (including every LoraTensor
    base) freezes. Same structure as ``params``."""

    def rule(x):
        if isinstance(x, LoraTensor):
            return LoraTensor(base="freeze", a="train", b="train",
                              scale=x.scale)
        return "freeze"

    return jax.tree.map(
        rule, params, is_leaf=lambda x: isinstance(x, LoraTensor)
    )


def lora_optimizer(
    tx: optax.GradientTransformation, params: Any
) -> optax.GradientTransformation:
    """Wrap ``tx`` so ONLY adapter leaves get optimizer state and
    updates: frozen leaves carry `set_to_zero` (no moments in HBM —
    the point of LoRA's memory win). The base's gradients are already
    zero (``lora_apply`` stop_gradient), this guarantees no optimizer
    bytes either."""
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()},
        lora_labels(params),
    )
