"""LoRA — low-rank adapters for parameter-efficient fine-tuning.

Full fine-tuning of a 7B model needs ~3 copies of every weight in HBM
(params + grads + Adam moments, ~80 GB fp32); LoRA trains only a pair
of rank-r factors per targeted matrix (``w ≈ w_base + a @ b · s``),
shrinking trainable state to well under 1% while the frozen base stays
a single read-only copy. The TPU shape of the idea:

- :class:`LoraTensor` is a registered pytree node (like
  ``quant.QuantTensor``), so LoRA-ified param trees ride jit,
  ``device_put``, mesh sharding, and orbax unchanged.
- The base matrix is wrapped in ``stop_gradient`` INSIDE the op, so XLA
  never builds the base-weight gradient matmuls — the backward pass
  costs scale with the adapters, not the model.
- :func:`lora_optimizer` masks the frozen leaves out of the optimizer
  with ``optax.multi_transform``, so Adam moments exist ONLY for the
  adapters — that is where the HBM win comes from.
- ``models/llama.py:QDense`` consumes ``LoraTensor`` kernels natively;
  ``llama_param_shardings`` shards ``base`` like the kernel it wraps
  and the factors along their matching halves, so FSDP/TP configs work
  untouched.

Reference parity note: the reference delegated all training machinery
to TF and had no parameter-efficient path (SURVEY.md §2.3); this is
capability beyond it, motivated by the same HBM arithmetic as
BASELINE.md's optimizer-state study.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import struct

DEFAULT_TARGETS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)


@struct.dataclass
class LoraTensor:
    """``w_eff = base + a @ b * scale`` with ``base`` frozen.

    ``base`` (in, out); ``a`` (in, r) gaussian-init; ``b`` (r, out)
    zero-init — so a freshly added adapter is an exact no-op (the
    standard LoRA init). ``scale`` = alpha / r, static.
    """

    base: jax.Array
    a: jax.Array
    b: jax.Array
    scale: float = struct.field(pytree_node=False, default=1.0)

    @property
    def shape(self):
        return self.base.shape

    @property
    def dtype(self):
        return self.base.dtype


def lora_apply(x: jax.Array, w: LoraTensor) -> jax.Array:
    """``x @ w_eff`` without materializing the merged matrix: the
    adapter path is two skinny matmuls (B·S·in·r + B·S·r·out FLOPs —
    negligible at r≪min(in,out)). ``stop_gradient`` on the base keeps
    the backward pass adapter-sized."""
    base = jax.lax.stop_gradient(w.base)
    y = x @ base.astype(x.dtype)
    lo = (x @ w.a.astype(x.dtype)) @ w.b.astype(x.dtype)
    return y + lo * w.scale


def add_lora(
    params: Any,
    rank: int,
    rng: jax.Array,
    targets: Sequence[str] = DEFAULT_TARGETS,
    alpha: float | None = None,
    dtype=jnp.float32,
) -> Any:
    """Wrap every 2-D leaf whose path contains a target name in a
    :class:`LoraTensor`. ``alpha`` defaults to ``rank`` (scale 1.0).
    The wrapped tree's forward output is EXACTLY the base tree's until
    the adapters train (b starts at zero)."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    scale = (alpha if alpha is not None else float(rank)) / float(rank)
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    keys = jax.random.split(rng, len(flat))

    def name_of(path) -> str:
        return "/".join(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path
        )

    out = []
    n_wrapped = 0
    for (path, leaf), key in zip(flat, keys):
        joined = name_of(path)
        if (
            hasattr(leaf, "ndim")
            and leaf.ndim == 2
            and any(t in joined for t in targets)
        ):
            d_in, d_out = leaf.shape
            if rank > min(d_in, d_out):
                raise ValueError(
                    f"rank {rank} exceeds min dim of {joined} {leaf.shape}"
                )
            a = (
                jax.random.normal(key, (d_in, rank), dtype)
                / jnp.sqrt(jnp.asarray(d_in, dtype))
            )
            b = jnp.zeros((rank, d_out), dtype)
            out.append(LoraTensor(base=leaf, a=a, b=b, scale=scale))
            n_wrapped += 1
        else:
            out.append(leaf)
    if n_wrapped == 0:
        raise ValueError(
            f"no 2-D params matched targets {tuple(targets)}; nothing to "
            "adapt"
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def merge_lora(params: Any) -> Any:
    """Fold trained adapters into plain kernels (``base + a@b·s``) for
    serving/export — zero inference overhead, and the merged tree is a
    drop-in for every consumer of the original params."""

    def rule(x):
        if isinstance(x, LoraTensor):
            merged = (
                x.base.astype(jnp.float32)
                + (x.a.astype(jnp.float32) @ x.b.astype(jnp.float32))
                * x.scale
            )
            return merged.astype(x.base.dtype)
        return x

    return jax.tree.map(
        rule, params, is_leaf=lambda x: isinstance(x, LoraTensor)
    )


def lora_labels(params: Any) -> Any:
    """'train' / 'freeze' label tree for ``optax.multi_transform``:
    adapter factors train, everything else (including every LoraTensor
    base) freezes. Same structure as ``params``."""

    def rule(x):
        if isinstance(x, LoraTensor):
            return LoraTensor(base="freeze", a="train", b="train",
                              scale=x.scale)
        return "freeze"

    return jax.tree.map(
        rule, params, is_leaf=lambda x: isinstance(x, LoraTensor)
    )


def lora_optimizer(
    tx: optax.GradientTransformation, params: Any
) -> optax.GradientTransformation:
    """Wrap ``tx`` so ONLY adapter leaves get optimizer state and
    updates: frozen leaves carry `set_to_zero` (no moments in HBM —
    the point of LoRA's memory win). The base's gradients are already
    zero (``lora_apply`` stop_gradient), this guarantees no optimizer
    bytes either."""
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()},
        lora_labels(params),
    )


@struct.dataclass
class MultiLoraTensor:
    """``w_eff(row) = base + a[id] @ b[id] * scale`` — a BANK of K
    adapters over one shared frozen base, routed per batch row.

    The serving shape of LoRA (S-LoRA style): one resident copy of the
    base weights serves many fine-tunes concurrently; each request picks
    its adapter by integer id. ``a`` (K, in, r), ``b`` (K, r, out).
    Per-row application gathers the two skinny factors for each row —
    O(B·(in+out)·r) bytes, trivial next to the base read — so rows with
    different adapters share one batched matmul against ``base``.

    Convention: make slot 0 a zero adapter (``b[0] == 0``) so plain
    requests route there and run the base model exactly (the
    :func:`multi_lora_bank` builder does this).
    """

    base: jax.Array
    a: jax.Array
    b: jax.Array
    scale: float = struct.field(pytree_node=False, default=1.0)

    @property
    def shape(self):
        return self.base.shape

    @property
    def dtype(self):
        return self.base.dtype

    @property
    def n_adapters(self) -> int:
        return self.a.shape[0]


def multi_lora_apply(
    x: jax.Array, w: MultiLoraTensor, adapter_ids: jax.Array
) -> jax.Array:
    """``x[i] @ w_eff(adapter_ids[i])`` for x (B, S, in), ids (B,).

    Same term order as :func:`lora_apply` (base matmul + two skinny
    adapter matmuls, scale applied last), so a row routed to adapter k
    matches a single-``LoraTensor`` run of that adapter bit-for-bit in
    shape and closely in rounding. The gathers materialize only the
    selected (B, in, r)/(B, r, out) factors, never a merged matrix."""
    base = jax.lax.stop_gradient(w.base)
    y = x @ base.astype(x.dtype)
    a_sel = jnp.take(w.a, adapter_ids, axis=0).astype(x.dtype)  # (B,in,r)
    b_sel = jnp.take(w.b, adapter_ids, axis=0).astype(x.dtype)  # (B,r,out)
    lo = jnp.einsum("bsd,bdr->bsr", x, a_sel)
    lo = jnp.einsum("bsr,bro->bso", lo, b_sel)
    return y + lo * w.scale


def multi_lora_bank(adapters: Sequence[Any]) -> Any:
    """Stack N single-adapter trees (from :func:`add_lora`, trained or
    not) into a served bank over the FIRST tree's bases.

    Slot 0 of the resulting bank is always the ZERO adapter (exact base
    model); trained adapters occupy slots 1..N. Every adapter must wrap
    the same kernels with the same rank and scale — mismatched trees
    (different targets/rank) fail loudly rather than mis-route."""
    if not adapters:
        raise ValueError("need at least one adapter tree")
    flats = [
        jax.tree_util.tree_flatten(
            t, is_leaf=lambda x: isinstance(x, LoraTensor)
        )
        for t in adapters
    ]
    treedef = flats[0][1]
    for i, (_, td) in enumerate(flats[1:], 1):
        if td != treedef:
            raise ValueError(
                f"adapter {i} has a different tree structure than "
                "adapter 0 (different LoRA targets?)"
            )
    out = []
    for leaves in zip(*(f[0] for f in flats)):
        first = leaves[0]
        if not isinstance(first, LoraTensor):
            out.append(first)
            continue
        for i, leaf in enumerate(leaves[1:], 1):
            if (
                leaf.a.shape != first.a.shape
                or leaf.scale != first.scale
            ):
                raise ValueError(
                    f"adapter {i} rank/scale mismatch: "
                    f"{leaf.a.shape}/{leaf.scale} vs "
                    f"{first.a.shape}/{first.scale}"
                )
            if leaf.base is not first.base:
                # Adapters fine-tuned from DIFFERENT base checkpoints
                # would silently serve on adapter 0's base. Same-object
                # is the common case (one tree add_lora'd N times); for
                # distinct arrays a 64-element sample comparison catches
                # a wrong checkpoint at bank-build time for microseconds.
                import numpy as np

                sa = np.asarray(leaf.base.ravel()[:64])
                sb = np.asarray(first.base.ravel()[:64])
                if not np.array_equal(sa, sb):
                    raise ValueError(
                        f"adapter {i} wraps a different base weight "
                        "than adapter 0 — all bank adapters must be "
                        "fine-tunes of the SAME base checkpoint"
                    )
        a = jnp.stack(
            [jnp.zeros_like(first.a)] + [l.a for l in leaves]
        )
        b = jnp.stack(
            [jnp.zeros_like(first.b)] + [l.b for l in leaves]
        )
        out.append(
            MultiLoraTensor(
                base=first.base, a=a, b=b, scale=first.scale
            )
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def select_adapter(params: Any, k: int) -> Any:
    """Slice adapter ``k`` out of a bank as a plain single-``LoraTensor``
    tree — the reference path for tests and for exporting one tenant's
    model (``merge_lora(select_adapter(bank, k))``)."""

    def rule(x):
        if isinstance(x, MultiLoraTensor):
            return LoraTensor(
                base=x.base, a=x.a[k], b=x.b[k], scale=x.scale
            )
        return x

    return jax.tree.map(
        rule, params, is_leaf=lambda x: isinstance(x, MultiLoraTensor)
    )


def bank_size(params: Any) -> int:
    """Number of adapter slots in a bank tree (0 = no bank present)."""
    n = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, MultiLoraTensor)
    ):
        if isinstance(leaf, MultiLoraTensor):
            n = max(n, leaf.n_adapters)
    return n


def rewrap_lora(tree: Any, scale: float = 1.0) -> Any:
    """Reconstruct LoRA pytree nodes from a checkpoint restored WITHOUT
    a target tree.

    Orbax returns plain nested dicts in that mode, so ``LoraTensor`` /
    ``MultiLoraTensor`` nodes come back as ``{"base", "a", "b"}`` dicts
    (the static ``scale`` field is not stored at all). This rewraps
    them — 2-D ``a`` → :class:`LoraTensor`, 3-D → :class:`MultiLoraTensor`
    bank — so a served checkpoint routes through the adapter paths
    again. ``scale`` must be re-supplied when the fine-tune used
    ``alpha != rank`` (the default ``add_lora`` scale is 1.0)."""

    def is_node(x):
        return isinstance(x, dict) and set(x) == {"base", "a", "b"}

    def rule(x):
        if is_node(x):
            cls = MultiLoraTensor if x["a"].ndim == 3 else LoraTensor
            return cls(base=x["base"], a=x["a"], b=x["b"], scale=scale)
        return x

    return jax.tree.map(rule, tree, is_leaf=is_node)
