"""Compute ops: attention kernels and friends.

The reference had no kernels of its own (all math delegated to TF —
SURVEY.md §1); here the hot ops get TPU-aware implementations: XLA-fused
defaults plus Pallas kernels where fusion isn't enough.
"""

from tensorflowonspark_tpu.ops.attention import dot_product_attention
from tensorflowonspark_tpu.ops.quant import (
    QuantTensor,
    dequantize_tree,
    quantize_tree,
    quantized_dot,
)

__all__ = [
    "dot_product_attention",
    "QuantTensor",
    "quantize_tree",
    "dequantize_tree",
    "quantized_dot",
]
