"""Pallas TPU kernels for BatchNorm channel statistics.

Why (measured, rounds 3-4, real v5e chip): the ResNet-50 train step spends
~45% of its time in XLA's `convert_reduce_fusion` ops — the BN statistics
reductions. The op *count* (~2 fused passes per BN layer) shows XLA already
merges the sibling reductions; the *rate* is the problem: the 97 reduce
fusions move ~9-14 GB of activations but take 44.5 ms/step, i.e. ~20-30%
of the chip's HBM streaming bandwidth (`benchmarks/results/` traces,
BASELINE.md analysis). These kernels pin the streaming loop explicitly —
one DMA'd (block_rows x block_cols) bf16 tile per grid step, fp32
accumulation in registers, per-channel partial sums revisiting a
VMEM-resident output block — so the stats passes run at the DMA rate the
flash-attention kernel in this package already demonstrates.

Two kernels, both reducing over all rows of a (rows, channels) view:

- ``pair_stats(x)``      -> (sum(x), sum(x*x))     : the forward pass
- ``cross_stats(dy, x)`` -> (sum(dy), sum(dy*x))   : the backward pass

The backward pass deliberately computes raw ``sum(dy*x)`` rather than
``sum(dy*xhat)`` so the kernel needs no per-channel scalar inputs; the
caller derives ``sum(dy*xhat) = invstd * (sum(dy*x) - mean*sum(dy))`` in
fp32 (same cancellation class as the one-pass variance, accepted and
documented in ops/batch_norm.py).

Round-5 status (measured, real v5e chip): IN-CONTEXT these kernels
REGRESS — ResNet-50 8.9% MFU vs 16.1% through the XLA reduces,
Inception-v3 13.7% vs 18.2%. The "slow" reduce fusions were amortized:
fused with neighboring elementwise work over conv outputs still resident
in the fusion; an opaque ``pallas_call`` severs that and forces extra
materialized activation round-trips that outweigh the streamed reduce's
rate win (full post-mortem in BASELINE.md). ``impl='auto'`` therefore
never picks these kernels; they remain for explicit standalone-stats
callers, where ``cross_stats`` measured ~2x the XLA reduce rate in
isolation.

Parity note: the reference delegated BN to TF's cuDNN fused kernels
(SURVEY.md §1 — no compute code of its own); this is the TPU-native
equivalent of that fused-statistics path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from tensorflowonspark_tpu.utils import compat

# Test hook: run the kernels in the Pallas interpreter (works on CPU).
INTERPRET = False

_OUT_SUBLANES = 8  # output blocks are (8, block_c): Mosaic's min f32 tile


def _choose_blocks(rows: int, cols: int) -> tuple[int, int]:
    """Tile choice: wide-ish lanes, ~1 MB bf16 input tiles.

    A 512-lane block keeps the DMA large while letting C=2048 layers
    partition cleanly. Narrow layers are real, not hypothetical —
    Inception-v3 BN sits at C=32/48/80/96 and the ResNet stem at C=64
    (models/inception.py, models/resnet.py) — so ``min(cols, 512)``
    passes sub-128-lane and non-128-aligned column blocks straight to
    Mosaic, which pads the lane dimension internally; those shapes are in
    ``benchmarks/pallas_bn_smoke.py``'s TPU list so a real-chip lowering
    failure shows up in the cheap smoke, not the conv-net compile. Rows
    default to 1024 (so a (1024, 512) bf16 tile is 1 MB — big enough to
    hit DMA streaming rate, small enough to double-buffer in VMEM).
    """
    block_c = min(cols, 512)
    block_r = min(rows, 1024)
    return block_r, block_c


def _accumulate(ref, value):
    ri = pl.program_id(1)

    @pl.when(ri == 0)
    def _():
        ref[...] = value

    @pl.when(ri > 0)
    def _():
        ref[...] += value


def _masked_rows(xf: jax.Array, rows: int, block_r: int) -> jax.Array:
    """Zero out rows past the array's true extent in the final partial
    block (zeros are exact identities for every statistic computed here)."""
    if rows % block_r == 0:
        return xf
    ri = pl.program_id(1)
    valid = rows - ri * block_r
    rid = lax.broadcasted_iota(jnp.int32, xf.shape, 0)
    return jnp.where(rid < valid, xf, 0.0)


def _pair_kernel(x_ref, sum_ref, sq_ref, *, rows: int, block_r: int):
    xf = _masked_rows(x_ref[...].astype(jnp.float32), rows, block_r)
    s = jnp.sum(xf, axis=0, keepdims=True)
    q = jnp.sum(xf * xf, axis=0, keepdims=True)
    _accumulate(sum_ref, jnp.broadcast_to(s, sum_ref.shape))
    _accumulate(sq_ref, jnp.broadcast_to(q, sq_ref.shape))


def _cross_kernel(dy_ref, x_ref, sdy_ref, sdyx_ref, *, rows: int, block_r: int):
    # Mask BOTH streams: a masked dy of 0 times a padded-garbage x (which
    # may be NaN) would still be NaN.
    dyf = _masked_rows(dy_ref[...].astype(jnp.float32), rows, block_r)
    xf = _masked_rows(x_ref[...].astype(jnp.float32), rows, block_r)
    s = jnp.sum(dyf, axis=0, keepdims=True)
    q = jnp.sum(dyf * xf, axis=0, keepdims=True)
    _accumulate(sdy_ref, jnp.broadcast_to(s, sdy_ref.shape))
    _accumulate(sdyx_ref, jnp.broadcast_to(q, sdyx_ref.shape))


def _stats_call(kernel, arrays, rows: int, cols: int):
    block_r, block_c = _choose_blocks(rows, cols)
    grid = (pl.cdiv(cols, block_c), pl.cdiv(rows, block_r))
    in_spec = pl.BlockSpec((block_r, block_c), lambda ci, ri: (ri, ci))
    # Output blocks revisit index (0, ci) across the (minor) row grid dim:
    # the accumulator stays VMEM-resident and flushes once per column block.
    out_spec = pl.BlockSpec((_OUT_SUBLANES, block_c), lambda ci, ri: (0, ci))
    out_shape = jax.ShapeDtypeStruct((_OUT_SUBLANES, cols), jnp.float32)
    a, b = pl.pallas_call(
        functools.partial(kernel, rows=rows, block_r=block_r),
        grid=grid,
        in_specs=[in_spec] * len(arrays),
        out_specs=[out_spec, out_spec],
        out_shape=[out_shape, out_shape],
        interpret=INTERPRET,
    )(*arrays)
    return a[0], b[0]


def _as_2d(x: jax.Array) -> jax.Array:
    return x.reshape(-1, x.shape[-1])


def pair_stats(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """One streamed pass over ``x`` viewed as (rows, C):
    per-channel ``(sum(x), sum(x*x))`` in fp32."""
    x2 = _as_2d(x)
    return _stats_call(_pair_kernel, (x2,), x2.shape[0], x2.shape[1])


def cross_stats(dy: jax.Array, x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """One streamed pass over ``(dy, x)`` viewed as (rows, C):
    per-channel ``(sum(dy), sum(dy*x))`` in fp32."""
    dy2, x2 = _as_2d(dy), _as_2d(x)
    assert dy2.shape == x2.shape, (dy2.shape, x2.shape)
    return _stats_call(_cross_kernel, (dy2, x2), x2.shape[0], x2.shape[1])


def use_pallas(impl: str = "auto") -> bool:
    """'pallas' | 'xla' | 'auto'.

    'auto' now ALWAYS resolves to the XLA sibling reduces. The round-5
    chip A/B falsified the kernels' in-context premise: ResNet-50
    measured 8.9% MFU through these kernels vs 16.1% through the XLA
    stats path (Inception-v3: 13.7% vs 18.2%) — an opaque
    ``pallas_call`` severs XLA's producer/consumer fusion around each
    BN layer, and the extra materialized activation round-trips cost
    more than the streamed reduce saves (BASELINE.md, "Where the
    ResNet-50 step goes"). The kernels remain for explicit
    ``impl='pallas'`` callers that use the stats standalone (the bwd
    ``cross_stats`` pair measured ~2× the XLA reduce rate in
    isolation) — where there is no surrounding fusion to sever.
    """
    if impl == "pallas":
        return True
    if impl == "xla":
        return False
    if impl != "auto":
        raise ValueError(f"impl must be pallas|xla|auto, got {impl!r}")
    return False


# Test hook, mirroring ops.attention.TREAT_AS_TPU: lets CI exercise the
# TPU-only dispatch decisions on the virtual CPU mesh with the Pallas
# interpreter. Read only at trace time in un-jitted resolvers.
TREAT_AS_TPU = False


def _on_tpu() -> bool:
    return TREAT_AS_TPU or jax.default_backend() == "tpu"


def stats_mesh(impl: str, batch_extent: int):
    """The ambient mesh, iff EXPLICIT ``impl='pallas'`` should take the
    shard_map route: per-shard Pallas partial sums + a psum over the
    batch axes. Returns None for "use use_pallas()'s answer".

    Keyed on explicit 'pallas' (not 'auto' — 'auto' always resolves to
    the XLA reduces since the round-5 regression measure, see
    :func:`use_pallas`): an explicit caller inside a jitted,
    GSPMD-sharded train step would otherwise hand a sharded operand to
    a raw ``pallas_call``, which GSPMD replicates — the shard_map route
    keeps the kernel's operands shard-local. Conditions: multi-device
    TPU, an ambient mesh published (``parallel.use_mesh`` — the
    train/eval-step builders do this during tracing), only batch-like
    axes sharded (conv activations shard the leading dim over
    ``(data, fsdp)``; a model/seq-sharded mesh means someone else owns
    the layout), not already inside a shard_map body, and the batch
    extent divisible over the mesh's batch axes.
    """
    if impl != "pallas":
        return None
    from tensorflowonspark_tpu.parallel.context import dispatch_mesh

    mesh = dispatch_mesh(
        _on_tpu,
        batch_extent,
        forbidden_axes=("pipe", "expert", "model", "seq"),
    )
    if mesh is None:
        return None
    # a trivial batch extent means the shard_map adds nothing over the
    # single-array path (and may strand the array on one device)
    if mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1) <= 1:
        return None
    return mesh


def _mesh_stats(stats_fn, arrays, mesh):
    """Place ``stats_fn`` (pair_stats/cross_stats) per-shard with
    shard_map — batch over ``(data, fsdp)``, everything else replicated —
    and psum the per-shard partial sums. Sums are exact identities under
    this split (each row lands in exactly one shard), so the result
    equals the single-device kernel up to fp32 summation order."""
    from tensorflowonspark_tpu.compute import layout

    axes = layout.BATCH_AXES
    spec = layout.batch_spec(arrays[0].ndim)

    def body(*arrs):
        a, b = stats_fn(*arrs)
        return lax.psum(a, axes), lax.psum(b, axes)

    fn = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * len(arrays),
        out_specs=(
            layout.activation_spec("replicated"),
            layout.activation_spec("replicated"),
        ),
        check_vma=False,
    )
    return fn(*arrays)


def mesh_pair_stats(x: jax.Array, mesh) -> tuple[jax.Array, jax.Array]:
    """:func:`pair_stats` on a batch-sharded multi-device mesh."""
    return _mesh_stats(pair_stats, (x,), mesh)


def mesh_cross_stats(
    dy: jax.Array, x: jax.Array, mesh
) -> tuple[jax.Array, jax.Array]:
    """:func:`cross_stats` on a batch-sharded multi-device mesh."""
    return _mesh_stats(cross_stats, (dy, x), mesh)
