"""Fused-statistics BatchNorm for bandwidth-bound TPU conv nets.

Why this exists (measured, round 3): on the real v5e chip, 48% of the
ResNet-50 train step is BatchNorm statistics reductions
(`convert_reduce_fusion` — see BASELINE.md's profile analysis), because the
autodiff-generated stats path makes several separate full passes over the
activations: mean and mean-of-squares forward, then sum(dy) and
sum(dy*xhat) backward, each its own HBM read of a (N,H,W,C) tensor, plus
the normalized-activation recompute. The convolutions themselves are only
~22% of the step (~76% MXU-efficient) — the stats traffic is the ceiling.

This module computes each direction's TWO channel statistics in ONE
variadic `lax.reduce` pass (XLA fuses the bf16→fp32 convert and the
squaring/products into the reduce's input), and pins the pass structure
with a `jax.custom_vjp` so autodiff cannot de-fuse it:

- forward: one pass over x for (sum, sum_sq) → mean/var; one fused
  normalize pass (read x, write y) in the model dtype.
- backward: one pass over (dy, x) for (sum_dy, sum_dy_xhat) — xhat is
  recomputed inline from the saved mean/invstd, never materialized — and
  one pass producing dx.

That is 2 reads + 1 write per direction beyond the convs' own traffic —
the streaming minimum for exact batch statistics.

Parity note: the reference delegated BN entirely to TF's library
(SURVEY.md §1 — it has no compute code of its own); this is the rebuild's
TPU-first equivalent of the cuDNN fused-BN kernels TF used on GPUs.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def _channel_stats(af: jax.Array, bf: jax.Array, reduce_dims: tuple[int, ...]):
    """One-pass per-channel (sum_a, sum_b), accumulated in fp32.

    Callers pass fp32 values built from the streamed tensor (convert
    FIRST, then square/multiply — squaring in bf16 loses the low bits
    that E[x²]−E[x]² cancellation needs). Two sibling reductions over
    inputs sharing the same streamed operand: XLA's multi-output fusion
    merges them into a single pass that reads the narrow tensor from HBM
    once, with the converts and products fused into the reduce input. A
    variadic ``lax.reduce`` would express the same thing explicitly, but
    this environment's remote TPU compile helper wedges on it (same
    class of quirk as the `remat_policy="dots"` note in BASELINE.md).
    """
    af = af.astype(jnp.float32)
    bf = bf.astype(jnp.float32)
    return jnp.sum(af, axis=reduce_dims), jnp.sum(bf, axis=reduce_dims)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_batch_norm(x, gamma, beta, eps):
    y, _, _ = _fbn_fwd_impl(x, gamma, beta, eps)
    return y


def _fbn_fwd_impl(x, gamma, beta, eps):
    mean, var = batch_norm_stats(x)
    invstd = lax.rsqrt(var + eps)
    # Normalize in the model dtype: scale/shift collapse to one fused
    # multiply-add over the streamed tensor.
    scale = (invstd * gamma.astype(jnp.float32)).astype(x.dtype)
    shift = (
        beta.astype(jnp.float32) - mean * invstd * gamma.astype(jnp.float32)
    ).astype(x.dtype)
    y = x * scale + shift
    return y, mean, invstd


def _fbn_fwd(x, gamma, beta, eps):
    y, mean, invstd = _fbn_fwd_impl(x, gamma, beta, eps)
    return y, (x, gamma, mean, invstd)


def _fbn_bwd(eps, res, dy):
    x, gamma, mean, invstd = res
    reduce_dims = tuple(range(x.ndim - 1))
    n = 1
    for d in reduce_dims:
        n *= x.shape[d]
    # xhat recomputed inline in fp32 register math (the HBM stream is
    # still the bf16 tensors; XLA fuses the converts); one pass reads
    # (dy, x) and yields both sums.
    xhat_f = (x.astype(jnp.float32) - mean) * invstd
    dy_f = dy.astype(jnp.float32)
    sum_dy, sum_dy_xhat = _channel_stats(dy_f, dy_f * xhat_f, reduce_dims)
    xhat = xhat_f.astype(x.dtype)

    gamma_f = gamma.astype(jnp.float32)
    # dx = gamma*invstd * (dy - sum_dy/n - xhat * sum_dy_xhat/n)
    a = (gamma_f * invstd).astype(x.dtype)
    b = (gamma_f * invstd * sum_dy / n).astype(x.dtype)
    c = (gamma_f * invstd * sum_dy_xhat / n).astype(x.dtype)
    dx = dy * a - b - xhat * c
    dgamma = sum_dy_xhat.astype(gamma.dtype)
    dbeta = sum_dy.astype(gamma.dtype)
    return dx, dgamma, dbeta


fused_batch_norm.defvjp(_fbn_fwd, _fbn_bwd)


def batch_norm_stats(x) -> tuple[jax.Array, jax.Array]:
    """One-pass (mean, var) over all-but-last dims, fp32."""
    reduce_dims = tuple(range(x.ndim - 1))
    n = 1
    for d in reduce_dims:
        n *= x.shape[d]
    xf = x.astype(jnp.float32)
    s, s2 = _channel_stats(xf, xf * xf, reduce_dims)
    mean = s / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    return mean, var


class FusedBatchNorm(nn.Module):
    """Drop-in for ``nn.BatchNorm`` on the conv-net train path.

    Train (``use_running_average=False``): normalizes with exact batch
    statistics via :func:`fused_batch_norm` (one stats pass per
    direction) and updates fp32 running stats under the standard
    ``batch_stats`` collection, with ``nn.BatchNorm``'s variable names
    (``mean``/``var``/``scale``/``bias``) and momentum convention. The
    flax auto-name of this class differs from ``nn.BatchNorm``'s
    (``FusedBatchNorm_N`` vs ``BatchNorm_N``), so the in-repo conv nets
    pass an explicit ``name="BatchNorm_N"`` to keep their checkpoint
    trees bit-compatible with the pre-swap era (see docs/SWITCHING.md
    "BatchNorm checkpoint compatibility"); do the same in new models if
    you need drop-in restore of ``nn.BatchNorm`` checkpoints. Eval:
    normalizes with the running stats — a pure elementwise chain XLA
    fuses on its own.
    """

    use_running_average: bool | None = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        use_avg = nn.merge_param(
            "use_running_average",
            self.use_running_average,
            use_running_average,
        )
        features = x.shape[-1]
        gamma = self.param("scale", nn.initializers.ones, (features,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (features,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )
        dtype = self.dtype or x.dtype
        x = x.astype(dtype)

        if use_avg:
            invstd = lax.rsqrt(ra_var.value + self.epsilon)
            scale = (invstd * gamma).astype(dtype)
            shift = (beta - ra_mean.value * invstd * gamma).astype(dtype)
            return x * scale + shift

        y = fused_batch_norm(x, gamma, beta, self.epsilon)
        if not self.is_initializing():
            # Running-stat update outside the custom_vjp (not part of the
            # differentiated path); one extra stats pass would double the
            # traffic, so reuse the forward's pass via stop_gradient-free
            # recompute: XLA CSEs this reduce with the one inside
            # fused_batch_norm's forward (identical subgraphs).
            mean, var = batch_norm_stats(x)
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return y
