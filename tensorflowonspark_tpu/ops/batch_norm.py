"""Fused-statistics BatchNorm for bandwidth-bound TPU conv nets.

Why this exists (measured, round 3): on the real v5e chip, 48% of the
ResNet-50 train step is BatchNorm statistics reductions
(`convert_reduce_fusion` — see BASELINE.md's profile analysis), because the
stats path makes several full passes over the activations: mean and
mean-of-squares forward, then sum(dy) and sum(dy*xhat) backward, each an
HBM read of a (N,H,W,C) tensor. The convolutions themselves are only ~22%
of the step (~76% MXU-efficient) — the stats traffic is the ceiling.

Round-4 finding (profiled A/B on the chip): XLA already merges the sibling
reductions into ~2 fused passes per layer — but runs them at ~20-30% of
HBM streaming rate. So the win looked like *pass rate*, not *pass
structure* (the round-3 custom-VJP re-derivation measured 15.8% MFU vs
flax BN's 16.1%), and `ops/bn_kernels.py` answered with Pallas streaming
kernels for the two stats passes.

Round-5 finding (the kernels' own chip A/B): the Pallas path REGRESSED
in-context — ResNet-50 8.9% vs 16.1%, Inception-v3 13.7% vs 18.2%. The
"slow" reduce fusions were amortized: fused with neighboring elementwise
work over inputs still resident from the producing conv. An opaque
``pallas_call`` severs that, forcing extra materialized activation
round-trips that cost more than the streamed reduce saves. ``impl='auto'``
therefore resolves to the XLA reduces everywhere; the kernels stay for
explicit ``impl='pallas'`` standalone-stats callers:

- forward: ONE kernel pass over x for per-channel (sum, sum_sq) → mean/var
  (fp32 accumulation over the bf16 stream); one fused normalize pass
  (read x, write y) in the model dtype, left to XLA.
- backward: ONE kernel pass over (dy, x) for (sum_dy, sum_dy_x) — xhat is
  never materialized; sum(dy·x̂) = invstd·(sum(dy·x) − mean·sum(dy)) in
  fp32 — and one XLA elementwise pass producing dx.

The statistics are computed exactly once per layer: `bn_train`'s custom
VJP computes them inside the op and returns them alongside the
normalized output, so the module reuses the same values for the
running-average update rather than recomputing and hoping for CSE.

Parity note: the reference delegated BN entirely to TF's library
(SURVEY.md §1 — it has no compute code of its own); this is the rebuild's
TPU-first equivalent of the cuDNN fused-BN kernels TF used on GPUs.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tensorflowonspark_tpu.ops import bn_kernels


def _channel_stats(af: jax.Array, bf: jax.Array, reduce_dims: tuple[int, ...]):
    """XLA-path per-channel (sum_a, sum_b), accumulated in fp32.

    Callers pass fp32 values built from the streamed tensor (convert
    FIRST, then square/multiply — squaring in bf16 loses the low bits
    that E[x²]−E[x]² cancellation needs). Two sibling reductions over
    inputs sharing the same streamed operand: XLA merges them into one
    multi-output reduce fusion. A variadic ``lax.reduce`` would express
    the same thing explicitly, but this environment's remote TPU compile
    helper wedges on it (same class of quirk as the `remat_policy="dots"`
    note in BASELINE.md).
    """
    af = af.astype(jnp.float32)
    bf = bf.astype(jnp.float32)
    return jnp.sum(af, axis=reduce_dims), jnp.sum(bf, axis=reduce_dims)


def _reduce_extent(x: jax.Array) -> int:
    n = 1
    for d in x.shape[:-1]:
        n *= d
    return n


def _resolve_impl(impl, x):
    """Resolve ``impl`` against the ambient state at trace time.

    Returns ``'pallas'`` | ``'xla'`` | ``('mesh_pallas', mesh)`` — the
    third is the multi-device route: per-shard Pallas partial sums +
    psum under shard_map (:func:`bn_kernels.stats_mesh` gates it). An
    already-resolved value (tuple, or explicit literal) passes through,
    so the custom-VJP backward re-resolving can never flip routes.
    """
    if isinstance(impl, tuple):
        return impl
    mesh = bn_kernels.stats_mesh(impl, x.shape[0])
    if mesh is not None:
        return ("mesh_pallas", mesh)
    return "pallas" if bn_kernels.use_pallas(impl) else "xla"


def batch_norm_stats(x, impl="auto") -> tuple[jax.Array, jax.Array]:
    """One-pass per-channel (mean, var) over all-but-last dims, fp32."""
    n = _reduce_extent(x)
    resolved = _resolve_impl(impl, x)
    if isinstance(resolved, tuple):
        s, s2 = bn_kernels.mesh_pair_stats(x, resolved[1])
    elif resolved == "pallas":
        s, s2 = bn_kernels.pair_stats(x)
    else:
        xf = x.astype(jnp.float32)
        s, s2 = _channel_stats(xf, xf * xf, tuple(range(x.ndim - 1)))
    mean = s / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    return mean, var


def bn_train(x, gamma, beta, eps, impl="auto"):
    """Train-mode BatchNorm: ``(y, mean, var)`` with exact batch stats.

    One streamed stats pass and one fused normalize pass forward; one
    streamed stats pass and one elementwise pass backward — the custom
    VJP implements the FULL BatchNorm gradient (including the terms from
    the statistics' dependence on ``x``) and pins the pass structure so
    autodiff cannot de-fuse it. The returned ``mean``/``var`` are for the
    running-average update; cotangents flowing into them are IGNORED
    (their contribution to the normalize is already inside the dx
    formula — that is train-mode BN's semantics, not an approximation).

    ``impl='auto'`` is resolved HERE, at forward-trace time, and the
    resolved literal is what the custom-VJP rules see — so a backward
    traced later (e.g. a ``jax.vjp`` callback after ambient state
    changed) can never pair a Pallas forward with an XLA backward or
    vice versa.
    """
    resolved = _resolve_impl(impl, x)
    return _bn_train(x, gamma, beta, eps, resolved)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, gamma, beta, eps, impl):
    y, mean, var, _ = _bn_train_fwd_impl(x, gamma, beta, eps, impl)
    return y, mean, var


def _bn_train_fwd_impl(x, gamma, beta, eps, impl):
    mean, var = batch_norm_stats(x, impl)
    invstd = lax.rsqrt(var + eps)
    gamma_f = gamma.astype(jnp.float32)
    # Normalize in the model dtype: scale/shift collapse to one fused
    # multiply-add over the streamed tensor.
    scale = (invstd * gamma_f).astype(x.dtype)
    shift = (beta.astype(jnp.float32) - mean * invstd * gamma_f).astype(x.dtype)
    y = x * scale + shift
    return y, mean, var, invstd


def _bn_train_fwd(x, gamma, beta, eps, impl):
    y, mean, var, invstd = _bn_train_fwd_impl(x, gamma, beta, eps, impl)
    return (y, mean, var), (x, gamma, mean, invstd)


def _bn_train_bwd(eps, impl, res, cts):
    # impl is the literal bn_train resolved at forward-trace time.
    dy, _dmean, _dvar = cts  # stats cotangents ignored — see bn_train.
    x, gamma, mean, invstd = res
    n = _reduce_extent(x)
    if isinstance(impl, tuple) or bn_kernels.use_pallas(impl):
        if isinstance(impl, tuple):
            sum_dy, sum_dy_x = bn_kernels.mesh_cross_stats(dy, x, impl[1])
        else:
            sum_dy, sum_dy_x = bn_kernels.cross_stats(dy, x)
        sum_dy_xhat = invstd * (sum_dy_x - mean * sum_dy)
        xhat = ((x.astype(jnp.float32) - mean) * invstd).astype(x.dtype)
    else:
        # xhat recomputed inline in fp32 register math (the HBM stream is
        # still the bf16 tensors; XLA fuses the converts); one pass reads
        # (dy, x) and yields both sums.
        reduce_dims = tuple(range(x.ndim - 1))
        xhat_f = (x.astype(jnp.float32) - mean) * invstd
        dy_f = dy.astype(jnp.float32)
        sum_dy, sum_dy_xhat = _channel_stats(dy_f, dy_f * xhat_f, reduce_dims)
        xhat = xhat_f.astype(x.dtype)

    gamma_f = gamma.astype(jnp.float32)
    # dx = gamma*invstd * (dy - sum_dy/n - xhat * sum_dy_xhat/n)
    a = (gamma_f * invstd).astype(x.dtype)
    b = (gamma_f * invstd * sum_dy / n).astype(x.dtype)
    c = (gamma_f * invstd * sum_dy_xhat / n).astype(x.dtype)
    dx = dy * a - b - xhat * c
    dgamma = sum_dy_xhat.astype(gamma.dtype)
    dbeta = sum_dy.astype(gamma.dtype)
    return dx, dgamma, dbeta


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def fused_batch_norm(x, gamma, beta, eps, impl: str = "auto"):
    """Batch-normalize with exact batch statistics (train-mode BN).

    Stats in one streamed pass, normalize in one fused elementwise pass;
    gradient via :func:`bn_train`'s custom VJP (one streamed stats pass +
    one elementwise pass).
    """
    y, _, _ = bn_train(x, gamma, beta, eps, impl)
    return y


class FusedBatchNorm(nn.Module):
    """Drop-in for ``nn.BatchNorm`` on the conv-net train path.

    Train (``use_running_average=False``): normalizes with exact batch
    statistics (one stats pass per direction — XLA multi-output reduce
    fusion by default; explicit ``impl='pallas'`` opts into the
    streaming kernels, see the module header) and updates fp32 running stats
    under the standard ``batch_stats`` collection, with ``nn.BatchNorm``'s
    variable names (``mean``/``var``/``scale``/``bias``) and momentum
    convention. The flax auto-name of this class differs from
    ``nn.BatchNorm``'s (``FusedBatchNorm_N`` vs ``BatchNorm_N``), so the
    in-repo conv nets pass an explicit ``name="BatchNorm_N"`` to keep
    their checkpoint trees bit-compatible with the pre-swap era (see
    docs/SWITCHING.md "BatchNorm checkpoint compatibility"); do the same
    in new models if you need drop-in restore of ``nn.BatchNorm``
    checkpoints. Eval: normalizes with the running stats — a pure
    elementwise chain XLA fuses on its own.
    """

    use_running_average: bool | None = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    impl: str = "auto"

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        use_avg = nn.merge_param(
            "use_running_average",
            self.use_running_average,
            use_running_average,
        )
        features = x.shape[-1]
        gamma = self.param("scale", nn.initializers.ones, (features,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (features,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )
        dtype = self.dtype or x.dtype
        x = x.astype(dtype)

        if use_avg:
            invstd = lax.rsqrt(ra_var.value + self.epsilon)
            scale = (invstd * gamma).astype(dtype)
            shift = (beta - ra_mean.value * invstd * gamma).astype(dtype)
            return x * scale + shift

        # Stats computed exactly ONCE inside the custom-VJP op: shared by
        # the normalize and the running-average update — explicitly, not
        # via CSE of a recompute.
        y, mean, var = bn_train(x, gamma, beta, self.epsilon, self.impl)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * lax.stop_gradient(mean)
            ra_var.value = m * ra_var.value + (1.0 - m) * lax.stop_gradient(var)
        return y
