"""Attention ops.

``dot_product_attention`` routes to the best available implementation:

- ``impl='xla'`` — plain einsum attention; XLA fuses softmax chains well
  and this is the safest default on CPU/testing.
- ``impl='flash'`` — the Pallas TPU flash-attention kernel from
  :mod:`tensorflowonspark_tpu.ops.flash_attention` (blockwise online
  softmax in VMEM; O(seq) memory).
- ``impl='auto'`` — flash on a single-device TPU when shapes allow; on a
  multi-device TPU with an ambient mesh (``parallel.use_mesh`` — the
  train-step builder publishes it during tracing), flash per-shard under
  ``shard_map`` with batch/head sharding (:func:`mesh_flash_attention`);
  otherwise xla, which GSPMD partitions fine.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu.utils import compat

# Test hook: lets CI exercise the TPU-only dispatch decisions (the
# mesh-flash route below) on the 8-device virtual CPU mesh with the
# Pallas interpreter. Read only in the un-jitted dispatcher, never inside
# a jitted function, so flipping it cannot leave stale traces behind.
TREAT_AS_TPU = False


def _on_tpu() -> bool:
    return TREAT_AS_TPU or jax.default_backend() == "tpu"


def _flash_shapes_ok(q, k, segment_ids) -> bool:
    """Shapes the Pallas flash kernel accepts (whole-array view)."""
    return (
        q.shape[1] >= 128
        and q.shape[1] % 128 == 0
        and k.shape[1] % 128 == 0
        and q.shape[3] >= 64
        # segment masking needs square attention (one id per position)
        and (segment_ids is None or q.shape[1] == k.shape[1])
    )


def _xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Reference attention: (B, Sq, H, D) x (B, Sk, H, D) -> (B, Sq, H, D).

    Supports grouped-query attention: k/v may have fewer heads than q as
    long as q_heads % kv_heads == 0.
    """
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    scale = (d**-0.5) if scale is None else scale
    if hq != hk:
        if hq % hk:
            raise ValueError(f"q heads {hq} not divisible by kv heads {hk}")
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        if window is not None:
            # sliding window: query i (absolute i + sk - sq) attends only
            # the last `window` keys — same end-aligned convention
            q_pos = jnp.arange(sq)[:, None] + (sk - sq)
            k_pos = jnp.arange(sk)[None, :]
            mask = mask & (q_pos - k_pos < window)
        logits = jnp.where(mask[None, None], logits, jnp.finfo(logits.dtype).min)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(
            seg_mask[:, None], logits, jnp.finfo(logits.dtype).min
        )
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    impl: str = "auto",
    window: int | None = None,
) -> jax.Array:
    """Multi-head attention with optional causal masking and GQA.

    Shapes: q (B, Sq, Hq, D); k/v (B, Sk, Hkv, D); returns (B, Sq, Hq, D).

    ``window`` restricts each query to the last ``window`` keys
    (sliding-window / Mistral-style local attention; requires
    ``causal=True``). All impls support it: xla/flash mask (the flash
    kernel also restricts its grids to the window span), ring shortens
    the rotation to the owners in reach (``parallel.ring_attention.
    ring_hops`` — O(window) ICI traffic per device), ulysses passes it
    to the per-device full-sequence attention.

    ``impl='ring'`` runs sequence-parallel ring attention over the ambient
    mesh's ``seq`` axis (set with ``parallel.use_mesh``); the mesh is a
    trace-time object, so this path is dispatched outside the jit cache —
    it is meant to be called from inside an outer jitted train step.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    if impl in ("ring", "ulysses"):
        from tensorflowonspark_tpu.parallel import current_mesh

        mesh = current_mesh()
        if mesh is None:
            raise ValueError(
                f"impl={impl!r} needs an ambient mesh; wrap the call (or "
                "the train-step trace) in "
                "tensorflowonspark_tpu.parallel.use_mesh"
            )
        if mesh.shape.get("seq", 1) == 1 and mesh.shape.get("model", 1) == 1:
            # re-enter the auto dispatcher (not _jitted_attention
            # directly) so degenerate ring/ulysses configs still get the
            # mesh-flash shard_map route on a multi-device batch mesh
            return dot_product_attention(
                q, k, v, causal=causal, scale=scale,
                segment_ids=segment_ids, impl="auto", window=window,
            )
        if impl == "ring":
            from tensorflowonspark_tpu.parallel import mesh_ring_attention

            # window ALSO shortens the ring: see ring_hops — a device
            # stops rotating once no reachable owner can contribute
            return mesh_ring_attention(
                q, k, v, mesh, causal=causal, scale=scale,
                segment_ids=segment_ids, window=window,
            )
        from tensorflowonspark_tpu.parallel import mesh_ulysses_attention

        return mesh_ulysses_attention(
            q, k, v, mesh, causal=causal, scale=scale,
            segment_ids=segment_ids, window=window,
        )
    if impl == "auto":
        mesh = _flash_mesh(q, k, segment_ids)
        if mesh is not None:
            return mesh_flash_attention(
                q, k, v, mesh, causal=causal, scale=scale,
                segment_ids=segment_ids, window=window,
            )
        impl = _local_auto_impl(q, k, segment_ids)
    return _jitted_attention(
        q, k, v, causal=causal, scale=scale,
        segment_ids=segment_ids, impl=impl, window=window,
    )


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "impl", "window")
)
def _jitted_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    impl: str = "auto",
    window: int | None = None,
) -> jax.Array:
    if impl == "auto":
        # 'auto' is resolved by the dispatcher (dot_product_attention:
        # _flash_mesh for the shard_map route, _local_auto_impl
        # otherwise) BEFORE this jitted function is entered — resolving
        # it here would fork the gate logic and bake trace-time ambient
        # state into the jit cache.
        raise ValueError(
            "impl='auto' must be resolved before _jitted_attention; "
            "call dot_product_attention instead"
        )
    if impl == "flash":
        from tensorflowonspark_tpu.ops.flash_attention import (
            flash_attention,
        )

        # positional: custom_vjp functions reject keyword arguments
        return flash_attention(
            q, k, v, causal, scale, None, None, window, segment_ids
        )
    return _xla_attention(
        q, k, v, causal=causal, scale=scale, segment_ids=segment_ids,
        window=window,
    )


def _local_auto_impl(q, k, segment_ids) -> str:
    """``auto`` for operands known to be shard-LOCAL: on a single-device
    process trivially, or inside a shard_map body (e.g. a ulysses or
    gpipe stage), where each device holds its own block — the raw flash
    kernel is safe there on any device count; the multi-device gate only
    guards GSPMD-sharded whole arrays."""
    try:
        local = len(jax.devices()) == 1
    except RuntimeError:  # pragma: no cover - no backend at all
        return "xla"
    if not local:
        try:
            local = jax.core.nonempty_axis_env_DO_NOT_USE()
        except AttributeError:  # pragma: no cover - future jax rename
            local = False
    return (
        "flash"
        if (_on_tpu() and local and _flash_shapes_ok(q, k, segment_ids))
        else "xla"
    )


def _flash_mesh(q, k, segment_ids):
    """The ambient mesh, iff ``auto`` should take the shard_map flash
    route: multi-device TPU, a published mesh whose only sharded axes are
    batch/head-like, and shapes the kernel accepts both globally and
    per-shard. Returns None for "resolve locally instead"."""
    from tensorflowonspark_tpu.parallel.context import dispatch_mesh

    # Only batch/head sharding: a sharded sequence wants ring/ulysses
    # (impl='ring'|'ulysses'), and pipe/expert bodies already run inside
    # a shard_map — nesting another would need a sub-mesh we don't have.
    mesh = dispatch_mesh(
        _on_tpu, q.shape[0], forbidden_axes=("pipe", "expert", "seq")
    )
    if mesh is None:
        return None
    tp = mesh.shape.get("model", 1)
    if q.shape[2] % tp or k.shape[2] % tp:
        return None
    if not _flash_shapes_ok(q, k, segment_ids):
        return None
    return mesh


def mesh_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    causal: bool = False,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Flash attention on a multi-device mesh via ``shard_map``.

    GSPMD cannot partition a ``pallas_call`` (the same limitation
    documented at :func:`bn_kernels.stats_mesh` and
    :func:`parallel.context.dispatch_mesh`): left inside a plain
    ``jit`` over a sharded mesh, the kernel's operands would be
    all-gathered onto every chip. Attention is embarrassingly parallel
    over batch and heads, so this wrapper places the kernel per-shard —
    batch over ``(data, fsdp)``, heads over ``model`` (K/V heads shard
    the same way, so GQA grouping stays intact per shard), sequence
    replicated (a sharded sequence wants ring/ulysses instead). No
    collectives run inside the body; the backward pass is the flash
    custom-VJP per shard, transposed by shard_map for free.

    Inputs are global arrays (B, S, H, D); B must divide the
    ``(data, fsdp)`` extent and both head counts the ``model`` extent
    (checked by the ``auto`` gate in :func:`_flash_mesh`; direct callers
    get shard_map's own divisibility errors).
    """
    from tensorflowonspark_tpu.compute import layout
    from tensorflowonspark_tpu.ops.flash_attention import flash_attention
    from tensorflowonspark_tpu.parallel.context import sp_specs_and_args

    spec = layout.activation_spec("attn_bshd")

    def body(q, k, v, segment_ids=None):
        # positional: custom_vjp functions reject keyword arguments
        return flash_attention(
            q, k, v, causal, scale, None, None, window, segment_ids
        )

    in_specs, args = sp_specs_and_args(spec, q, k, v, segment_ids)
    fn = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec,
        check_vma=False,
    )
    return fn(*args)
