"""Attention ops.

``dot_product_attention`` routes to the best available implementation:

- ``impl='xla'`` — plain einsum attention; XLA fuses softmax chains well
  and this is the safest default on CPU/testing.
- ``impl='flash'`` — the Pallas TPU flash-attention kernel from
  :mod:`tensorflowonspark_tpu.ops.flash_attention` (blockwise online
  softmax in VMEM; O(seq) memory).
- ``impl='auto'`` — flash on TPU when shapes allow, else xla.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Reference attention: (B, Sq, H, D) x (B, Sk, H, D) -> (B, Sq, H, D).

    Supports grouped-query attention: k/v may have fewer heads than q as
    long as q_heads % kv_heads == 0.
    """
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    scale = (d**-0.5) if scale is None else scale
    if hq != hk:
        if hq % hk:
            raise ValueError(f"q heads {hq} not divisible by kv heads {hk}")
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        if window is not None:
            # sliding window: query i (absolute i + sk - sq) attends only
            # the last `window` keys — same end-aligned convention
            q_pos = jnp.arange(sq)[:, None] + (sk - sq)
            k_pos = jnp.arange(sk)[None, :]
            mask = mask & (q_pos - k_pos < window)
        logits = jnp.where(mask[None, None], logits, jnp.finfo(logits.dtype).min)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(
            seg_mask[:, None], logits, jnp.finfo(logits.dtype).min
        )
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    impl: str = "auto",
    window: int | None = None,
) -> jax.Array:
    """Multi-head attention with optional causal masking and GQA.

    Shapes: q (B, Sq, Hq, D); k/v (B, Sk, Hkv, D); returns (B, Sq, Hq, D).

    ``window`` restricts each query to the last ``window`` keys
    (sliding-window / Mistral-style local attention; requires
    ``causal=True``). All impls support it: xla/flash mask (the flash
    kernel also restricts its grids to the window span), ring shortens
    the rotation to the owners in reach (``parallel.ring_attention.
    ring_hops`` — O(window) ICI traffic per device), ulysses passes it
    to the per-device full-sequence attention.

    ``impl='ring'`` runs sequence-parallel ring attention over the ambient
    mesh's ``seq`` axis (set with ``parallel.use_mesh``); the mesh is a
    trace-time object, so this path is dispatched outside the jit cache —
    it is meant to be called from inside an outer jitted train step.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    if impl in ("ring", "ulysses"):
        from tensorflowonspark_tpu.parallel import current_mesh

        mesh = current_mesh()
        if mesh is None:
            raise ValueError(
                f"impl={impl!r} needs an ambient mesh; wrap the call (or "
                "the train-step trace) in "
                "tensorflowonspark_tpu.parallel.use_mesh"
            )
        if mesh.shape.get("seq", 1) == 1 and mesh.shape.get("model", 1) == 1:
            return _jitted_attention(
                q, k, v, causal=causal, scale=scale,
                segment_ids=segment_ids, impl="auto", window=window,
            )
        if impl == "ring":
            from tensorflowonspark_tpu.parallel import mesh_ring_attention

            # window ALSO shortens the ring: see ring_hops — a device
            # stops rotating once no reachable owner can contribute
            return mesh_ring_attention(
                q, k, v, mesh, causal=causal, scale=scale,
                segment_ids=segment_ids, window=window,
            )
        from tensorflowonspark_tpu.parallel import mesh_ulysses_attention

        return mesh_ulysses_attention(
            q, k, v, mesh, causal=causal, scale=scale,
            segment_ids=segment_ids, window=window,
        )
    return _jitted_attention(
        q, k, v, causal=causal, scale=scale,
        segment_ids=segment_ids, impl=impl, window=window,
    )


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "impl", "window")
)
def _jitted_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    impl: str = "auto",
    window: int | None = None,
) -> jax.Array:
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        shapes_ok = (
            q.shape[1] >= 128
            and q.shape[1] % 128 == 0
            and k.shape[1] % 128 == 0
            and q.shape[3] >= 64
            # segment masking needs square attention (one id per position)
            and (segment_ids is None or q.shape[1] == k.shape[1])
        )
        impl = "flash" if (on_tpu and shapes_ok) else "xla"
    if impl == "flash":
        from tensorflowonspark_tpu.ops.flash_attention import (
            flash_attention,
        )

        # positional: custom_vjp functions reject keyword arguments
        return flash_attention(
            q, k, v, causal, scale, None, None, window, segment_ids
        )
    return _xla_attention(
        q, k, v, causal=causal, scale=scale, segment_ids=segment_ids,
        window=window,
    )
