"""Pallas TPU flash attention (blockwise online-softmax, fwd + bwd).

The kernels stream one (block_q x block_k) tile per grid step, keeping the
O(Sq x Sk) logits matrix out of HBM entirely — the standard flash recipe
expressed for the MXU/VPU split (matmuls in the MXU, the online-softmax
rescale on the VPU). See /opt/skills/guides/pallas_guide.md for the kernel
idioms used here.

Memory shape: the K-block (or Q-block, in backward) index is a *grid*
dimension — innermost, so accumulators live in VMEM scratch across steps —
which keeps VMEM pressure at O(block x d) regardless of sequence length.
GQA is a BlockSpec index-map (each Q head reads its KV group's block
directly from HBM), not a materialized ``jnp.repeat``.

Backward follows FlashAttention's two-pass scheme against saved
log-sum-exp residuals: a dQ kernel (grid over Q blocks, streaming K), and
a dK/dV kernel (grid over K blocks, streaming Q). dK/dV are computed per
*query* head and group-summed outside the kernel — inside, multiple grid
rows would otherwise race on one KV head's output block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# Per-row stats (LSE, delta) are stored lane-replicated to NUM_LANES so
# their blocks satisfy Mosaic's (8, 128) tiling rule — a (1, block_q)
# block on a (rows, seq) array is rejected on real TPUs. Same layout the
# reference TPU kernel in jax.experimental.pallas.ops.tpu uses. Segment
# ids ride the same way: q ids lane-replicated, kv ids sublane-replicated
# (so the kernel reads a (1, block_k) row without a transpose).
NUM_LANES = 128
NUM_SUBLANES = 8

# Test hook: run the kernel in the Pallas interpreter (works on CPU).
INTERPRET = False


def _causal_live(qi, ki, block_q: int, block_k: int, offset: int):
    """This (Q, K) block pair intersects the causal frontier."""
    return ki * block_k <= (qi + 1) * block_q - 1 + offset


def _window_live(qi, ki, block_q, block_k, offset, window):
    """This block pair has keys inside the sliding window's lower edge
    (query i attends j >= i + offset - window + 1)."""
    return (ki + 1) * block_k - 1 >= qi * block_q + offset - (window - 1)


def _window_grid_k(window, block_q, block_k, num_k_blocks):
    """K-block grid extent per q block under a window: the live key span
    of one q block is block_q + window - 1 elements, so this many blocks
    always cover it (+1 for alignment slack). The grid — and therefore
    the K/V block DMAs — shrinks with it: windowed cost is O(S·W) in
    BOTH compute and HBM traffic, not just masked-out compute."""
    if window is None:
        return num_k_blocks
    return min(num_k_blocks, (block_q + window - 2) // block_k + 2)


def _first_k_block(qi, offset, window, block_q, block_k, nk, num_k_blocks):
    """First k block of this q block's restricted span, clamped so the
    nk-wide span stays inside [0, num_k_blocks). Blocks pulled in by the
    clamp are dead and get masked by the live/window checks."""
    first = (qi * block_q + offset - (window - 1)) // block_k
    return jnp.clip(first, 0, num_k_blocks - nk)


def _window_grid_q(window, block_q, block_k, num_q_blocks):
    """Q-block grid extent per k block (the dkv kernel's restriction)."""
    if window is None:
        return num_q_blocks
    return min(num_q_blocks, (block_k + window - 2) // block_q + 2)


def _first_q_block(ki, offset, window, block_q, block_k, nq, num_q_blocks):
    """First q block that can attend this k block (the causal lower edge
    q >= k - offset), clamped like :func:`_first_k_block`."""
    first = (ki * block_k - offset) // block_q
    return jnp.clip(first, 0, num_q_blocks - nq)


def _tile_logits(
    q, k, qi, ki, block_q, block_k, offset, causal, scale, window=None
):
    """Scaled (block_q, block_k) logits with the causal (and optional
    sliding-window) mask applied."""
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if causal or window is not None:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if causal:
            s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
        if window is not None:
            s = jnp.where(q_pos + offset - k_pos < window, s, NEG_INF)
    return s


def _segment_masked(s, qseg_ref, kseg_ref, block_k: int):
    """Mask logits where q and k segment ids differ (trace-time no-op
    when no segment refs are bound). The online-softmax rescale makes a
    leading fully-masked tile harmless: its uniform exp(0) garbage is
    zeroed by alpha the moment a live tile raises the running max."""
    if qseg_ref is None:
        return s
    q_ids = qseg_ref[0]  # (block_q, NUM_LANES), lane-replicated
    if block_k % NUM_LANES == 0:
        q_ids = jnp.tile(q_ids, (1, block_k // NUM_LANES))
    else:  # short sequences: block_k < one lane tile
        q_ids = q_ids[:, :block_k]
    k_ids = kseg_ref[0][:1, :]  # (1, block_k) from the sublane-replicated row
    return jnp.where(q_ids == k_ids, s, NEG_INF)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(
    *refs, block_q: int, block_k: int, seq_q: int, seq_k: int,
    causal: bool, scale: float, num_k_blocks: int, has_segments: bool,
    window: int | None = None,
):
    if has_segments:
        (q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    kr = pl.program_id(2)  # restricted index: kr-th block of the window span
    offset = seq_k - seq_q
    nk = _window_grid_k(window, block_q, block_k, num_k_blocks)
    if window is None:
        ki = kr
    else:
        ki = kr + _first_k_block(
            qi, offset, window, block_q, block_k, nk, num_k_blocks
        )

    @pl.when(kr == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # End-aligned causal semantics (matches the XLA path's tril(k=sk-sq)):
    # query i attends keys j <= i + (sk - sq).
    live = (
        _causal_live(qi, ki, block_q, block_k, offset) if causal else ki >= 0
    )
    if window is not None:
        live = live & _window_live(qi, ki, block_q, block_k, offset, window)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = _tile_logits(
            q, k, qi, ki, block_q, block_k, offset, causal, scale, window
        )
        s = _segment_masked(s, qseg_ref, kseg_ref, block_k)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kr == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            m_ref[...] + jnp.log(l), (o_ref.shape[1], NUM_LANES)
        )


def _segment_operands(segment_ids, sq: int, sk: int):
    """Broadcast (B, S) segment ids into the kernel layouts: q ids
    lane-replicated (B, Sq, NUM_LANES), kv ids sublane-replicated
    (B, NUM_SUBLANES, Sk)."""
    b = segment_ids.shape[0]
    seg = segment_ids.astype(jnp.int32)
    qseg = jax.lax.broadcast_in_dim(seg, (b, sq, NUM_LANES), (0, 1))
    kseg = jax.lax.broadcast_in_dim(seg, (b, NUM_SUBLANES, sk), (0, 2))
    return qseg, kseg


def _check_segment_ids(segment_ids, b: int, sq: int, sk: int) -> None:
    if segment_ids is None:
        return
    if sq != sk:
        raise ValueError(
            "segment_ids needs sq == sk (one id array covers both sides)"
        )
    if segment_ids.shape != (b, sq):
        raise ValueError(
            f"segment_ids shape {segment_ids.shape} != {(b, sq)}"
        )


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    scale: float | None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    return_lse: bool = False,
    segment_ids: jax.Array | None = None,
    window: int | None = None,
):
    """(B, Sq, H, D) attention with GQA head broadcast, Pallas forward."""
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    scale = (d**-0.5) if scale is None else scale
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash attention needs seq lengths divisible by block sizes: "
            f"sq={sq} block_q={block_q}, sk={sk} block_k={block_k}; "
            "pad sequences or use impl='xla'"
        )
    if hq % hk:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hk}")
    _check_segment_ids(segment_ids, b, sq, sk)
    group = hq // hk

    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, q-head); K/V
    # stay at their kv-head count — the index map does the GQA broadcast.
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)

    num_k_blocks = sk // block_k
    nk_w = _window_grid_k(window, block_q, block_k, num_k_blocks)
    grid = (b * hq, sq // block_q, nk_w)

    def k_block(qi, kr):
        # restricted ki grid -> actual k block (windowed kernels DMA
        # only the ~window-span K/V blocks per q block)
        if window is None:
            return kr
        return kr + _first_k_block(
            qi, sk - sq, window, block_q, block_k, nk_w, num_k_blocks
        )

    def kv_row(h, qi, kr):
        # grid row h = batch * hq + q_head; its KV row in the (b*hk) array
        return (h // hq) * hk + (h % hq) // group, k_block(qi, kr), 0

    kernel = functools.partial(
        _fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        seq_q=sq,
        seq_k=sk,
        causal=causal,
        scale=scale,
        num_k_blocks=num_k_blocks,
        has_segments=segment_ids is not None,
        window=window,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda h, qi, ki: (h, qi, 0)),
        pl.BlockSpec((1, block_k, d), kv_row),
        pl.BlockSpec((1, block_k, d), kv_row),
    ]
    operands = [qt, kt, vt]
    if segment_ids is not None:
        in_specs += [
            pl.BlockSpec(
                (1, block_q, NUM_LANES), lambda h, qi, kr: (h // hq, qi, 0)
            ),
            pl.BlockSpec(
                (1, NUM_SUBLANES, block_k),
                lambda h, qi, kr: (h // hq, 0, k_block(qi, kr)),
            ),
        ]
        operands += list(_segment_operands(segment_ids, sq, sk))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, qi, ki: (h, qi, 0)),
            pl.BlockSpec((1, block_q, NUM_LANES), lambda h, qi, ki: (h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * hq, sq, NUM_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running denominator
        ],
        interpret=INTERPRET,
    )(*operands)
    out = out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
    if return_lse:
        return out, lse[:, :, 0]
    return out


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _probs(s, lse_col):
    """p = exp(s - lse), zeroed for fully-masked rows.

    A row with no live keys has lse = NEG_INF, and ``NEG_INF - NEG_INF``
    would make every masked entry exp(0) = 1. The forward emits 0 for such
    rows (a constant), so their correct gradient contribution is exactly 0.
    """
    return jnp.where(lse_col > NEG_INF / 2, jnp.exp(s - lse_col), 0.0)


def _dq_kernel(
    *refs, block_q: int, block_k: int, seq_q: int, seq_k: int,
    causal: bool, scale: float, num_k_blocks: int, has_segments: bool,
    window: int | None = None,
):
    if has_segments:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         qseg_ref, kseg_ref, dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    kr = pl.program_id(2)
    offset = seq_k - seq_q
    nk = _window_grid_k(window, block_q, block_k, num_k_blocks)
    if window is None:
        ki = kr
    else:
        ki = kr + _first_k_block(
            qi, offset, window, block_q, block_k, nk, num_k_blocks
        )

    @pl.when(kr == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (
        _causal_live(qi, ki, block_q, block_k, offset) if causal else ki >= 0
    )
    if window is not None:
        live = live & _window_live(qi, ki, block_q, block_k, offset, window)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = _tile_logits(
            q, k, qi, ki, block_q, block_k, offset, causal, scale, window
        )
        s = _segment_masked(s, qseg_ref, kseg_ref, block_k)
        p = _probs(s, lse_ref[0][:, :1])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0][:, :1])
        dq_acc[...] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kr == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(
    *refs, block_q: int, block_k: int, seq_q: int, seq_k: int,
    causal: bool, scale: float, num_q_blocks: int, has_segments: bool,
    window: int | None = None,
):
    if has_segments:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qseg_ref = kseg_ref = None
    ki = pl.program_id(1)
    qr = pl.program_id(2)
    offset = seq_k - seq_q
    nq = _window_grid_q(window, block_q, block_k, num_q_blocks)
    if window is None:
        qi = qr
    else:
        qi = qr + _first_q_block(
            ki, offset, window, block_q, block_k, nq, num_q_blocks
        )

    @pl.when(qr == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (
        _causal_live(qi, ki, block_q, block_k, offset) if causal else qi >= 0
    )
    if window is not None:
        live = live & _window_live(qi, ki, block_q, block_k, offset, window)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = _tile_logits(
            q, k, qi, ki, block_q, block_k, offset, causal, scale, window
        )
        s = _segment_masked(s, qseg_ref, kseg_ref, block_k)
        p = _probs(s, lse_ref[0][:, :1])  # (block_q, block_k)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0][:, :1])
        dk_acc[...] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qr == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, out, lse, g, causal, scale,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    segment_ids: jax.Array | None = None,
    window: int | None = None,
):
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    scale = (d**-0.5) if scale is None else scale
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    group = hq // hk

    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)
    ot = out.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    gt = g.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    # delta_i = rowsum(dO_i * O_i): cheap elementwise; XLA fuses it.
    delta = jnp.sum(
        gt.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1
    )
    # Lane-replicate the per-row stats so their blocks tile legally (see
    # NUM_LANES above).
    lse_l = jnp.broadcast_to(lse[:, :, None], (b * hq, sq, NUM_LANES))
    delta_l = jnp.broadcast_to(delta[:, :, None], (b * hq, sq, NUM_LANES))
    seg_operands: list = []
    if segment_ids is not None:
        seg_operands = list(_segment_operands(segment_ids, sq, sk))

    num_q_blocks = sq // block_q
    num_k_blocks = sk // block_k
    nk_w = _window_grid_k(window, block_q, block_k, num_k_blocks)
    nq_w = _window_grid_q(window, block_q, block_k, num_q_blocks)

    def kv_row3(h, a, c):
        return (h // hq) * hk + (h % hq) // group

    def k_block(qi, kr):
        if window is None:
            return kr
        return kr + _first_k_block(
            qi, sk - sq, window, block_q, block_k, nk_w, num_k_blocks
        )

    def q_block(ki, qr):
        if window is None:
            return qr
        return qr + _first_q_block(
            ki, sk - sq, window, block_q, block_k, nq_w, num_q_blocks
        )

    common = dict(
        block_q=block_q,
        block_k=block_k,
        seq_q=sq,
        seq_k=sk,
        causal=causal,
        scale=scale,
        window=window,
    )

    has_segments = segment_ids is not None
    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda h, qi, kr: (h, qi, 0)),
        pl.BlockSpec(
            (1, block_k, d),
            lambda h, qi, kr: (kv_row3(h, qi, kr), k_block(qi, kr), 0),
        ),
        pl.BlockSpec(
            (1, block_k, d),
            lambda h, qi, kr: (kv_row3(h, qi, kr), k_block(qi, kr), 0),
        ),
        pl.BlockSpec((1, block_q, d), lambda h, qi, kr: (h, qi, 0)),
        pl.BlockSpec((1, block_q, NUM_LANES), lambda h, qi, kr: (h, qi, 0)),
        pl.BlockSpec((1, block_q, NUM_LANES), lambda h, qi, kr: (h, qi, 0)),
    ]
    if has_segments:
        dq_in_specs += [
            pl.BlockSpec(
                (1, block_q, NUM_LANES), lambda h, qi, kr: (h // hq, qi, 0)
            ),
            pl.BlockSpec(
                (1, NUM_SUBLANES, block_k),
                lambda h, qi, kr: (h // hq, 0, k_block(qi, kr)),
            ),
        ]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            num_k_blocks=num_k_blocks,
            has_segments=has_segments,
            **common,
        ),
        grid=(b * hq, num_q_blocks, nk_w),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda h, qi, kr: (h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=INTERPRET,
    )(qt, kt, vt, gt, lse_l, delta_l, *seg_operands)

    # dK/dV per *query* head (b*hq rows): several q heads share one KV head,
    # and revisiting an output block from non-consecutive grid rows is not
    # allowed — group-sum afterwards instead.
    dkv_in_specs = [
        pl.BlockSpec(
            (1, block_q, d), lambda h, ki, qr: (h, q_block(ki, qr), 0)
        ),
        pl.BlockSpec(
            (1, block_k, d), lambda h, ki, qr: (kv_row3(h, ki, qr), ki, 0)
        ),
        pl.BlockSpec(
            (1, block_k, d), lambda h, ki, qr: (kv_row3(h, ki, qr), ki, 0)
        ),
        pl.BlockSpec(
            (1, block_q, d), lambda h, ki, qr: (h, q_block(ki, qr), 0)
        ),
        pl.BlockSpec(
            (1, block_q, NUM_LANES),
            lambda h, ki, qr: (h, q_block(ki, qr), 0),
        ),
        pl.BlockSpec(
            (1, block_q, NUM_LANES),
            lambda h, ki, qr: (h, q_block(ki, qr), 0),
        ),
    ]
    if has_segments:
        dkv_in_specs += [
            pl.BlockSpec(
                (1, block_q, NUM_LANES),
                lambda h, ki, qr: (h // hq, q_block(ki, qr), 0),
            ),
            pl.BlockSpec(
                (1, NUM_SUBLANES, block_k), lambda h, ki, qr: (h // hq, 0, ki)
            ),
        ]
    dk_q, dv_q = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            num_q_blocks=num_q_blocks,
            has_segments=has_segments,
            **common,
        ),
        grid=(b * hq, num_k_blocks, nq_w),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda h, ki, qr: (h, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, ki, qr: (h, ki, 0)),
        ],
        out_shape=[
            # f32: the group-sum below must accumulate in full precision —
            # bf16 kernel outputs would round before the reduction.
            jax.ShapeDtypeStruct((b * hq, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b * hq, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=INTERPRET,
    )(qt, kt, vt, gt, lse_l, delta_l, *seg_operands)

    dq = dq.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
    dk = (
        dk_q.reshape(b, hk, group, sk, d).sum(axis=2).transpose(0, 2, 1, 3)
    ).astype(k.dtype)
    dv = (
        dv_q.reshape(b, hk, group, sk, d).sum(axis=2).transpose(0, 2, 1, 3)
    ).astype(v.dtype)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public op
# --------------------------------------------------------------------------


def _default_blocks(sq: int, sk: int) -> tuple[int, int]:
    """Block sizes by sequence length, measured on v5e: bigger blocks
    amortize grid overhead once the sequence is long enough (512 wins at
    >=4k, 256 at >=1k, 128 below)."""

    def pick(s):
        for cand in (512, 256, 128):
            if s >= 4096 and cand == 512 and s % cand == 0:
                return cand
            if s >= 1024 and cand == 256 and s % cand == 0:
                return cand
        return 128

    return pick(sq), pick(sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    window: int | None = None,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Flash attention; ``segment_ids`` (B, S) masks cross-segment
    attention for packed sequences (requires sq == sk). ``window``
    restricts each query to the last ``window`` keys (sliding-window /
    Mistral-style local attention; requires ``causal=True``) — blocks
    entirely below the window edge are skipped, so cost is O(S·W)."""
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    bq, bk = _default_blocks(q.shape[1], k.shape[1])
    return _flash_forward(
        q, k, v, causal, scale, block_q or bq, block_k or bk,
        segment_ids=segment_ids, window=window,
    )


def _fwd(q, k, v, causal, scale, block_q, block_k, window, segment_ids):
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1"
        )
    bq, bk = _default_blocks(q.shape[1], k.shape[1])
    out, lse = _flash_forward(
        q, k, v, causal, scale, block_q or bq, block_k or bk,
        return_lse=True, segment_ids=segment_ids, window=window,
    )
    return out, (q, k, v, out, lse, segment_ids)


def _bwd(causal, scale, block_q, block_k, window, res, g):
    q, k, v, out, lse, segment_ids = res
    bq, bk = _default_blocks(q.shape[1], k.shape[1])
    dq, dk, dv = _flash_backward(
        q, k, v, out, lse, g, causal, scale, block_q or bq, block_k or bk,
        segment_ids=segment_ids, window=window,
    )
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)
