"""Pallas TPU flash attention (blockwise online-softmax forward).

The kernel streams K/V blocks through VMEM against one Q block per grid
step, keeping the O(Sq x Sk) logits matrix out of HBM entirely — the
standard flash recipe expressed for the MXU/VPU split (matmuls in the MXU,
the online-softmax rescale on the VPU). See /opt/skills/guides/
pallas_guide.md for the kernel idioms used here.

Round-1 scope: the forward pass is Pallas; the backward pass recomputes
attention with the XLA implementation via ``jax.custom_vjp`` (correct, but
O(S^2) memory in backward). A Pallas backward kernel is planned.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30

# Test hook: run the kernel in the Pallas interpreter (works on CPU).
INTERPRET = False


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_k: int, seq_q: int,
    causal: bool, scale: float, block_q: int
):
    qi = pl.program_id(1)  # q-block index
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    d = q.shape[-1]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = seq_k // block_k
    # End-aligned causal semantics (matches the XLA path's tril(k=sk-sq)):
    # query i attends keys j <= i + (sk - sq).
    offset = seq_k - seq_q
    if causal:
        # Only K blocks at or before this Q block's diagonal contribute.
        num_live = jnp.minimum(
            ((qi + 1) * block_q + offset + block_k - 1) // block_k,
            num_k_blocks,
        )
    else:
        num_live = num_k_blocks

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_live, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    scale: float | None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """(B, Sq, H, D) attention with GQA head broadcast, Pallas forward."""
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    scale = (d**-0.5) if scale is None else scale
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash attention needs seq lengths divisible by block sizes: "
            f"sq={sq} block_q={block_q}, sk={sk} block_k={block_k}; "
            "pad sequences or use impl='xla'"
        )
    if hq % hk:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hk}")
    if hq != hk:
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)

    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, head)
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hq, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hq, sk, d)

    grid = (b * hq, sq // block_q)

    kernel = functools.partial(
        _flash_fwd_kernel,
        block_k=block_k,
        seq_k=sk,
        seq_q=sq,
        causal=causal,
        scale=scale,
        block_q=block_q,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, qi: (h, qi, 0)),
            pl.BlockSpec((1, sk, d), lambda h, qi: (h, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda h, qi: (h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda h, qi: (h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hq, sq, d), q.dtype),
        interpret=INTERPRET,
    )(qt, kt, vt)
    return out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    return _flash_forward(q, k, v, causal, scale)


def _fwd(q, k, v, causal, scale):
    return _flash_forward(q, k, v, causal, scale), (q, k, v)


def _bwd(causal, scale, res, g):
    from tensorflowonspark_tpu.ops.attention import _xla_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _xla_attention(q, k, v, causal=causal, scale=scale),
        q,
        k,
        v,
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
