"""Pallas TPU flash attention (blockwise online-softmax forward).

The kernel streams one (block_q x block_k) tile per grid step, keeping the
O(Sq x Sk) logits matrix out of HBM entirely — the standard flash recipe
expressed for the MXU/VPU split (matmuls in the MXU, the online-softmax
rescale on the VPU). See /opt/skills/guides/pallas_guide.md for the kernel
idioms used here.

Memory shape: the K-block index is a *grid* dimension (innermost, so the
online-softmax state lives in VMEM scratch across K steps), which keeps
VMEM pressure at O(block_q x d + block_k x d) regardless of sequence
length — full-length K/V staging would cap usable context at a few K
tokens. GQA is a BlockSpec index-map (each Q head reads its KV group's
block directly from HBM), not a materialized ``jnp.repeat``.

Round-1 scope: the forward pass is Pallas; the backward pass recomputes
attention with the XLA implementation via ``jax.custom_vjp`` (correct, but
O(S^2) memory in backward). A Pallas backward kernel is planned.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30

# Test hook: run the kernel in the Pallas interpreter (works on CPU).
INTERPRET = False


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
    block_q: int, block_k: int, seq_q: int, seq_k: int,
    causal: bool, scale: float, num_k_blocks: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # End-aligned causal semantics (matches the XLA path's tril(k=sk-sq)):
    # query i attends keys j <= i + (sk - sq).
    offset = seq_k - seq_q
    if causal:
        # K blocks strictly past this Q block's diagonal contribute nothing
        # — skip their MXU work entirely.
        live = ki * block_k <= (qi + 1) * block_q - 1 + offset
    else:
        live = ki >= 0  # always true, as a traced predicate

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos + offset >= k_pos, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        ).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    scale: float | None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """(B, Sq, H, D) attention with GQA head broadcast, Pallas forward."""
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    scale = (d**-0.5) if scale is None else scale
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash attention needs seq lengths divisible by block sizes: "
            f"sq={sq} block_q={block_q}, sk={sk} block_k={block_k}; "
            "pad sequences or use impl='xla'"
        )
    if hq % hk:
        raise ValueError(f"q heads {hq} not divisible by kv heads {hk}")
    group = hq // hk

    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, q-head); K/V
    # stay at their kv-head count — the index map does the GQA broadcast.
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)

    num_k_blocks = sk // block_k
    grid = (b * hq, sq // block_q, num_k_blocks)

    def kv_row(h, qi, ki):
        # grid row h = batch * hq + q_head; its KV row in the (b*hk) array
        return (h // hq) * hk + (h % hq) // group, ki, 0

    kernel = functools.partial(
        _flash_fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        seq_q=sq,
        seq_k=sk,
        causal=causal,
        scale=scale,
        num_k_blocks=num_k_blocks,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, qi, ki: (h, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_row),
            pl.BlockSpec((1, block_k, d), kv_row),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda h, qi, ki: (h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hq, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running denominator
        ],
        interpret=INTERPRET,
    )(qt, kt, vt)
    return out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    return _flash_forward(q, k, v, causal, scale)


def _fwd(q, k, v, causal, scale):
    return _flash_forward(q, k, v, causal, scale), (q, k, v)


def _bwd(causal, scale, res, g):
    from tensorflowonspark_tpu.ops.attention import _xla_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _xla_attention(q, k, v, causal=causal, scale=scale),
        q,
        k,
        v,
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
