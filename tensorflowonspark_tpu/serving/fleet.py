"""Fault-tolerant serving fleet: health-routed engine replicas.

``serve_model`` fronted ONE :class:`ContinuousBatcher` — a single
``EngineWedged`` or SIGKILL took down serving for every user. This
module owns N engine replicas the way the TensorFlow paper composes
workers behind a coordinator (TF-Replicator's framing: the client sees
one engine, the system owns N):

- **Replica handles** — :class:`InProcessReplica` (a factory-built
  engine in this process; each has its own scheduler + watchdog) and
  :class:`SubprocessReplica` (a ``serve_model`` child process reached
  over HTTP; the unit a SIGKILL can take out without touching its
  peers). Both expose the same surface: ``submit_many`` / ``stream`` /
  ``stats`` / ``health`` / ``metrics_text``.

- **Health plane** — a probe loop on the liveness cadence (the PR-4
  heartbeat discipline applied to replicas): each round reads
  ``health()`` (liveness vs readiness, the split ``/healthz`` now
  serves) and ``/stats``; consecutive misses, a dead liveness bit, or
  a watchdog-fire delta (the ``EngineWedged`` signal) flip the replica
  to DRAINING — in-flight requests run out or fail over at the router,
  new load reroutes — and the supervisor respawns it. Rejoin is gated
  on warmup-complete READINESS, never on process existence: a replica
  that is still compiling serves nobody.

- **States** — ``STARTING → READY ⇄ DRAINING → (respawn) → STARTING``,
  terminally ``DEAD`` after ``max_respawns`` failed spawns. Exposed as
  the ``fleet_replica_state`` gauge (labels ``replica``, ``state``) and
  as flightrec events ``replica_drain`` / ``replica_respawn`` (dumped
  on incident, so a postmortem reads the transition log).

The router (:mod:`tensorflowonspark_tpu.serving.router`) consumes the
fleet's snapshots for placement/admission and reports request-path
failures back through :meth:`ServingFleet.report_failure`.

Locking: each seat's mutable state is guarded by its OWN lock (fine-
grained — a slow probe of one replica must not serialize placement);
the fleet lock guards only the fleet-wide flags. Seat locks and the
fleet lock are never held together.

Failpoints: ``fleet.replica_probe`` (a raised probe is a missed beat),
``fleet.replica_spawn`` (a raised spawn exercises the respawn retry /
DEAD path); ``fleet.dispatch`` lives in the router.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

from tensorflowonspark_tpu.obs import flightrec, reqtrace
from tensorflowonspark_tpu.obs import registry as obs_registry
from tensorflowonspark_tpu.serving.engine import (
    DeadlineExceeded,
    EngineOverloaded,
    EngineWedged,
)
from tensorflowonspark_tpu.utils.failpoints import failpoint

logger = logging.getLogger(__name__)

__all__ = [
    "DEAD",
    "DRAINING",
    "READY",
    "STARTING",
    "FleetOverloaded",
    "FleetUnavailable",
    "InProcessReplica",
    "ReplicaGone",
    "ServingFleet",
    "SubprocessReplica",
]

# Replica lifecycle states (strings: they label the state gauge and
# ride JSON health bodies verbatim).
STARTING = "starting"  # spawned, warming up — not yet routable
READY = "ready"  # serving traffic
DRAINING = "draining"  # unhealthy or retiring: no new load, in-flight
# runs out or fails over, supervisor respawn in progress
DEAD = "dead"  # respawn budget exhausted — operator attention
_STATES = (STARTING, READY, DRAINING, DEAD)


class FleetOverloaded(RuntimeError):
    """Admission shed: no replica can meet the request's deadline (or
    every replica's queue is full). Retryable after ``retry_after``
    seconds — ``serve_model`` maps this to HTTP 429 + Retry-After."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = max(1.0, float(retry_after))


class FleetUnavailable(RuntimeError):
    """No READY replica exists (full-fleet drain, or everything is
    respawning/dead). ``serve_model`` maps this to HTTP 503."""


class ReplicaGone(RuntimeError):
    """The replica died under a request (process SIGKILLed, transport
    cut, engine closed mid-flight). Failover-eligible at the router
    while the request is still idempotent; terminal otherwise."""


# -- replica handles ---------------------------------------------------------


class InProcessReplica:
    """One factory-built :class:`ContinuousBatcher` in this process.

    The factory runs at :meth:`start` (and again on every respawn — a
    respawned replica is a FRESH engine: cold prefix cache, fresh
    scheduler/watchdog, compiled programs rebuilt), so a wedged
    engine's state can never leak into its successor.
    """

    kind = "inproc"

    def __init__(self, rid: int, factory, *, warmup: bool = True):
        self.rid = int(rid)
        self._factory = factory
        self._warmup = bool(warmup)
        self.engine = None

    def start(self) -> None:
        failpoint("fleet.replica_spawn")
        engine = self._factory()
        try:
            if self._warmup:
                engine.warmup()
        except BaseException:
            engine.close()
            raise
        self.engine = engine

    # -- health/obs ----------------------------------------------------

    def health(self) -> dict:
        if self.engine is None:
            return {"live": False, "ready": False}
        return self.engine.health()

    def stats(self) -> dict:
        if self.engine is None:
            raise ReplicaGone(f"replica {self.rid} has no engine")
        return self.engine.stats()

    def metrics_text(self) -> str:
        if self.engine is None:
            return ""
        return self.engine.metrics.render()

    # -- request path --------------------------------------------------

    def submit_many(self, prompts, max_new_tokens, **kw):
        eng = self.engine
        if eng is None:
            raise ReplicaGone(f"replica {self.rid} has no engine")
        try:
            return eng.submit_many(prompts, max_new_tokens, **kw)
        except RuntimeError as e:
            if isinstance(e, (EngineWedged, EngineOverloaded)):
                raise
            if "shutting down" in str(e):
                # raced the drain/close: the request was never accepted
                # — idempotent by construction, let the router fail over
                raise ReplicaGone(
                    f"replica {self.rid} closed during dispatch"
                ) from e
            raise

    def stream(self, tokens, max_new_tokens, **kw):
        eng = self.engine
        if eng is None:
            raise ReplicaGone(f"replica {self.rid} has no engine")
        try:
            return eng.stream(tokens, max_new_tokens, **kw)
        except RuntimeError as e:
            if isinstance(e, (EngineWedged, EngineOverloaded)):
                raise
            if "shutting down" in str(e):
                raise ReplicaGone(
                    f"replica {self.rid} closed during dispatch"
                ) from e
            raise

    # -- lifecycle -----------------------------------------------------

    def unresolved(self) -> int:
        return 0 if self.engine is None else self.engine.unresolved()

    def terminate(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Retire the engine: ``drain=True`` lets accepted requests run
        out (the watchdog has already aborted them with terminal
        ``EngineWedged`` if it fired — drain then returns fast)."""
        eng, self.engine = self.engine, None
        if eng is not None:
            eng.close(drain=drain, drain_timeout=timeout)

    def kill(self) -> None:
        self.terminate(drain=False)


class SubprocessReplica:
    """One ``serve_model`` child process reached over HTTP.

    The process-isolation unit: a SIGKILL (OOM kill, operator
    ``kill -9``, chaos test) takes out exactly one replica; the fleet's
    probe loop sees the missed beats and respawns it. ``spawn_argv``
    is the child's ``serve_model`` CLI (checkpoint, engine knobs);
    ``--port 0 --port-file`` are appended here — the child binds an
    ephemeral port AFTER its engine is built (and warmed, with
    ``--gen-warmup``), so the port file doubles as the spawn barrier.
    """

    kind = "subprocess"

    def __init__(
        self,
        rid: int,
        spawn_argv: list[str],
        *,
        spawn_timeout: float = 180.0,
        request_timeout: float = 120.0,
        probe_timeout: float = 2.0,
        env: dict | None = None,
        admin_token: str | None = None,
    ):
        self.rid = int(rid)
        self._argv = list(spawn_argv)
        self._spawn_timeout = float(spawn_timeout)
        self._request_timeout = float(request_timeout)
        self._probe_timeout = float(probe_timeout)
        self._env = dict(env) if env is not None else None
        # shared secret for the child's /admin/reload (weight hot-swap);
        # injected into the child env at spawn so only this supervisor
        # can drive reloads
        self._admin_token = admin_token
        self.proc: subprocess.Popen | None = None
        self.port: int | None = None

    def start(self) -> None:
        failpoint("fleet.replica_spawn")
        fd, port_file = tempfile.mkstemp(prefix="tfos-replica-port-")
        os.close(fd)
        os.remove(port_file)  # the child creates it at bind time
        argv = [
            sys.executable,
            "-m",
            "tensorflowonspark_tpu.tools.serve_model",
            *self._argv,
            "--port",
            "0",
            "--port-file",
            port_file,
        ]
        env = dict(os.environ if self._env is None else self._env)
        if self._admin_token is not None:
            env["TFOS_ADMIN_TOKEN"] = self._admin_token
        self.proc = subprocess.Popen(
            argv,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env=env,
        )
        deadline = time.monotonic() + self._spawn_timeout
        try:
            while True:
                if self.proc.poll() is not None:
                    raise ReplicaGone(
                        f"replica {self.rid} child exited rc="
                        f"{self.proc.returncode} before binding"
                    )
                try:
                    with open(port_file, "r", encoding="utf-8") as f:
                        text = f.read().strip()
                    if text:
                        self.port = int(text)
                        return
                except OSError:
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica {self.rid} child did not bind within "
                        f"{self._spawn_timeout}s"
                    )
                time.sleep(0.05)
        except BaseException:
            self.kill()
            raise
        finally:
            try:
                os.remove(port_file)
            except OSError:
                pass

    # -- HTTP plumbing -------------------------------------------------

    def _url(self, path: str) -> str:
        if self.port is None:
            raise ReplicaGone(f"replica {self.rid} is not running")
        return f"http://127.0.0.1:{self.port}{path}"

    def _get_json(self, path: str, timeout: float) -> dict:
        try:
            with urllib.request.urlopen(
                self._url(path), timeout=timeout
            ) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except ReplicaGone:
            raise
        except Exception as e:  # noqa: BLE001 - transport = replica gone
            raise ReplicaGone(
                f"replica {self.rid} GET {path} failed: "
                f"{type(e).__name__}: {e}"
            ) from e

    def _post(
        self,
        path: str,
        payload: dict,
        timeout: float,
        headers: dict | None = None,
    ):
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self._url(path),
            data=body,
            headers={
                "Content-Type": "application/json",
                **(headers or {}),
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(
                    resp.read().decode("utf-8")
                )
        except urllib.error.HTTPError as e:
            try:
                err_payload = json.loads(e.read().decode("utf-8"))
            except Exception:  # noqa: BLE001 - torn error body
                err_payload = {"error": str(e)}
            return e.code, err_payload
        except Exception as e:  # noqa: BLE001 - transport = replica gone
            raise ReplicaGone(
                f"replica {self.rid} POST {path} failed: "
                f"{type(e).__name__}: {e}"
            ) from e

    @staticmethod
    def _raise_mapped(status: int, payload: dict) -> None:
        """Reconstruct the engine-typed error a replica's HTTP status
        encodes (``serve_model`` stamps ``error_type`` beside the
        message for exactly this round trip)."""
        msg = str(payload.get("error", f"HTTP {status}"))
        etype = payload.get("error_type", "")
        if status == 400:
            raise ValueError(msg)
        if status == 504 or etype == "DeadlineExceeded":
            raise DeadlineExceeded(msg)
        if etype == "EngineWedged":
            raise EngineWedged(msg)
        if etype == "EngineOverloaded" or "queue full" in msg:
            raise EngineOverloaded(msg)
        raise ReplicaGone(f"HTTP {status}: {msg}")

    # -- health/obs ----------------------------------------------------

    def health(self) -> dict:
        try:
            h = self._get_json("/healthz", self._probe_timeout)
        except ReplicaGone:
            return {"live": False, "ready": False}
        h.setdefault("live", True)
        h.setdefault("ready", True)
        return h

    def stats(self) -> dict:
        return self._get_json("/stats", self._probe_timeout)

    def metrics_text(self) -> str:
        try:
            with urllib.request.urlopen(
                self._url("/metrics"), timeout=self._probe_timeout
            ) as resp:
                return resp.read().decode("utf-8", "replace")
        except ReplicaGone:
            raise
        except Exception as e:  # noqa: BLE001 - transport = replica gone
            raise ReplicaGone(
                f"replica {self.rid} GET /metrics failed: "
                f"{type(e).__name__}: {e}"
            ) from e

    # -- request path --------------------------------------------------

    @staticmethod
    def _request_body(prompts, max_new_tokens, kw) -> dict:
        body = {"prompts": prompts, "max_new_tokens": int(max_new_tokens)}
        for key in (
            "temperature",
            "eos_id",
            "adapter",
            "stop",
            "top_k",
            "top_p",
            "seed",
            "min_p",
            "frequency_penalty",
            "presence_penalty",
            "deadline_s",
        ):
            if kw.get(key) is not None:
                body[key] = kw[key]
        if kw.get("logit_bias") is not None:
            body["logit_bias"] = {
                str(t): v for t, v in kw["logit_bias"].items()
            }
        if kw.get("return_logprobs") or kw.get("yield_logprobs"):
            body["logprobs"] = True
        if kw.get("return_versions"):
            body["versions"] = True
        return body

    def submit_many(self, prompts, max_new_tokens, **kw):
        # the trace id crosses the process boundary as a header, not a
        # body field — the child's ingress adopts it exactly like any
        # external caller's X-TFOS-Trace
        trace = kw.pop("trace", None)
        body = self._request_body(prompts, max_new_tokens, kw)
        timeout = self._request_timeout
        if kw.get("deadline_s") is not None:
            # the HTTP wait must outlive the engine's own deadline so
            # the typed 504 (not a socket timeout) is what comes back
            timeout = max(timeout, float(kw["deadline_s"]) + 30.0)
        status, payload = self._post(
            "/generate",
            body,
            timeout,
            headers={reqtrace.HEADER: trace} if trace else None,
        )
        if status != 200:
            self._raise_mapped(status, payload)
        out: tuple = (payload["completions"],)
        if kw.get("return_logprobs"):
            out += (payload["logprobs"],)
        if kw.get("return_versions"):
            out += (payload.get("weights_versions"),)
        return out if len(out) > 1 else out[0]

    def stream(self, tokens, max_new_tokens, **kw):
        trace = kw.pop("trace", None)
        body = self._request_body([tokens], max_new_tokens, kw)
        body["stream"] = True
        timeout = self._request_timeout
        if kw.get("deadline_s") is not None:
            # like submit_many: a long-deadline request whose first
            # token legitimately waits must come back as the typed
            # DeadlineExceeded, not a socket timeout masquerading as
            # a dead replica (which would drain a healthy one)
            timeout = max(timeout, float(kw["deadline_s"]) + 30.0)
        return _HTTPStream(
            self, body, bool(kw.get("yield_logprobs")), timeout,
            trace=trace,
        )

    def reload(
        self,
        *,
        version: str,
        kind: str = "full",
        path: str,
        step: int | None = None,
        timeout: float = 600.0,
    ) -> dict:
        """Hot-swap the child's serving weights through its
        authenticated ``/admin/reload`` (the child loads ``path`` — an
        orbax checkpoint directory — itself, swaps between decode
        blocks, and re-warms before answering). Raises
        :class:`~tensorflowonspark_tpu.serving.engine.WeightsIncompatible`
        on a shape/layout mismatch (HTTP 409) so a rollout controller
        can trigger rollback, :class:`ReplicaGone` on transport death."""
        if self._admin_token is None:
            raise RuntimeError(
                f"replica {self.rid} has no admin token; spawn it "
                "through a ServingFleet (or pass admin_token=)"
            )
        body: dict = {"version": str(version), "kind": kind, "path": path}
        if step is not None:
            body["step"] = int(step)
        status, payload = self._post(
            "/admin/reload",
            body,
            timeout,
            headers={"Authorization": f"Bearer {self._admin_token}"},
        )
        if status != 200:
            from tensorflowonspark_tpu.serving.engine import (
                WeightsIncompatible,
            )

            msg = str(payload.get("error", f"HTTP {status}"))
            if (
                status == 409
                or payload.get("error_type") == "WeightsIncompatible"
            ):
                raise WeightsIncompatible(msg)
            raise RuntimeError(
                f"replica {self.rid} reload failed: HTTP {status}: {msg}"
            )
        return payload

    # -- lifecycle -----------------------------------------------------

    def unresolved(self) -> int:
        try:
            st = self.stats()
        except ReplicaGone:
            return 0  # a dead process resolves nothing further
        # the engine's own accounting (accepted - completed - failed),
        # served at /stats — queued requests are accepted but not yet
        # "admitted", and cancelled/wedged requests resolve through
        # completed/failed, so deriving this from the admission
        # counters here would be wrong on both ends
        return max(0, int(st.get("unresolved", 0)))

    def terminate(self, drain: bool = True, timeout: float = 30.0) -> None:
        proc = self.proc
        if proc is None:
            self.port = None
            return
        if drain and proc.poll() is None:
            # the child has no graceful-SIGTERM path (serve_model's
            # drain hook runs on KeyboardInterrupt only), so draining
            # means WAITING: poll the engine's /stats unresolved count
            # down to zero (bounded) before the terminate — a dead or
            # unreachable child reads 0 and falls straight through
            deadline = time.monotonic() + timeout
            while (
                time.monotonic() < deadline
                and proc.poll() is None
                and self.unresolved() > 0
            ):
                time.sleep(0.1)
        self.proc = None
        self.port = None
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
        else:
            proc.wait()  # reap

    def kill(self) -> None:
        proc, self.proc = self.proc, None
        self.port = None
        if proc is not None:
            if proc.poll() is None:
                proc.kill()
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass

    @property
    def pid(self) -> int | None:
        return None if self.proc is None else self.proc.pid


class _HTTPStream:
    """Iterator over a subprocess replica's NDJSON ``/generate``
    stream, mirroring the engine's ``_Stream`` surface (``close`` /
    ``result`` / ``logprobs``). A severed connection or an EOF without
    the done-trailer is a LOUD :class:`ReplicaGone` — a SIGKILLed
    replica's consumers get exactly one terminal, never a silent
    hang."""

    _conn = None  # class default: __del__ must be safe when the
    # constructor raised before the connection existed

    def __init__(self, replica, body, yield_logprobs, timeout, trace=None):
        self._rid = replica.rid
        self._yield_logprobs = yield_logprobs
        self._done = False
        self.result = None
        self.logprobs = None
        self.weights_version = None  # from the done-trailer
        headers = {"Content-Type": "application/json"}
        if trace:
            headers[reqtrace.HEADER] = trace
        try:
            self._conn = http.client.HTTPConnection(
                "127.0.0.1", replica.port, timeout=timeout
            )
            self._conn.request(
                "POST",
                "/generate",
                json.dumps(body),
                headers,
            )
            self._resp = self._conn.getresponse()
        except Exception as e:  # noqa: BLE001 - transport = replica gone
            raise ReplicaGone(
                f"replica {replica.rid} stream connect failed: "
                f"{type(e).__name__}: {e}"
            ) from e
        if self._resp.status != 200:
            try:
                payload = json.loads(self._resp.read().decode("utf-8"))
            except Exception:  # noqa: BLE001 - torn error body
                payload = {"error": f"HTTP {self._resp.status}"}
            self._conn.close()
            SubprocessReplica._raise_mapped(self._resp.status, payload)

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        try:
            raw = self._resp.readline()
        except Exception as e:  # noqa: BLE001 - severed mid-stream
            self._done = True
            self._conn.close()
            raise ReplicaGone(
                f"replica {self._rid} stream severed: "
                f"{type(e).__name__}: {e}"
            ) from e
        if not raw:
            self._done = True
            self._conn.close()
            raise ReplicaGone(
                f"replica {self._rid} stream ended without a terminal"
            )
        try:
            line = json.loads(raw)
        except ValueError as e:
            # a torn line (the replica died mid-write) is the SAME
            # severed-stream verdict as an EOF — it must surface as
            # the failover-eligible ReplicaGone, not a JSONDecodeError
            # that bypasses failure reporting
            self._done = True
            self._conn.close()
            raise ReplicaGone(
                f"replica {self._rid} stream severed mid-line: "
                f"{raw[:64]!r}"
            ) from e
        if line.get("done"):
            self._done = True
            self.result = line.get("completion")
            self.logprobs = line.get("logprobs")
            self.weights_version = line.get("weights_version")
            self._conn.close()
            raise StopIteration
        if "error" in line:
            self._done = True
            self._conn.close()
            etype = line.get("error_type", "")
            msg = str(line["error"])
            if etype == "EngineWedged" or msg.startswith("EngineWedged"):
                raise EngineWedged(msg)
            if etype == "DeadlineExceeded" or msg.startswith(
                "DeadlineExceeded"
            ):
                raise DeadlineExceeded(msg)
            raise ReplicaGone(msg)
        if self._yield_logprobs:
            return line["token"], line.get("logprob")
        return line["token"]

    def close(self) -> None:
        # closing the connection is the cancel signal: the server's
        # stream writer hits BrokenPipe and closes the engine stream
        if not getattr(self, "_done", True):
            self._done = True
            try:
                if self._conn is not None:
                    self._conn.close()
            except Exception:  # noqa: BLE001 - already gone
                pass

    __del__ = close


# -- the fleet ---------------------------------------------------------------


class _ReplicaSlot:
    """Fleet-side bookkeeping for one replica seat, guarded by the
    seat's OWN lock (fine-grained: one slow seat must not serialize
    the others). The seat is stable (rid never changes); the handle
    behind it is replaced on respawn (``generation`` bumps)."""

    def __init__(self, rid: int, handle):
        self.rid = rid
        self._lock = threading.Lock()
        self.handle = handle  # guarded-by: self._lock
        self.state = STARTING  # guarded-by: self._lock
        self.misses = 0  # guarded-by: self._lock
        self.last_stats: dict = {}  # guarded-by: self._lock
        self.last_watchdog = 0  # guarded-by: self._lock
        self.generation = 0  # guarded-by: self._lock
        self.respawns = 0  # lifetime respawn attempts  # guarded-by: self._lock
        # CONSECUTIVE failed spawn attempts — the DEAD budget counts
        # these, reset on every successful install, so a seat that
        # respawns successfully N times over weeks never goes DEAD
        self.spawn_failures = 0  # guarded-by: self._lock
        # True while a rollout controller holds the seat in DRAINING
        # (hold_seat/release_seat): the respawn supervisor must leave a
        # held seat alone — the holder owns its lifecycle
        self.hold = False  # guarded-by: self._lock
        self.last_reason: str | None = None  # guarded-by: self._lock
        # last probe-round health verdict (fleet.health() serves THIS
        # instead of re-probing every replica per call)
        self.last_health: dict = {"live": True, "ready": True}  # guarded-by: self._lock

    def view(self) -> dict:
        """Point-in-time snapshot, handed out as a plain dict
        (``rid`` / ``state`` / ``stats`` / ``handle`` /
        ``generation``) — the router and observability surfaces never
        touch live slot fields."""
        with self._lock:
            return {
                "rid": self.rid,
                "state": self.state,
                "stats": dict(self.last_stats),
                "handle": self.handle,
                "generation": self.generation,
            }

    def seat_info(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "generation": self.generation,
                "respawns": self.respawns,
                "misses": self.misses,
                "last_reason": self.last_reason,
                "stats": dict(self.last_stats),
            }

    def health_view(self) -> dict:
        """Per-seat liveness/readiness from the CACHED probe verdict —
        no replica IO (a /healthz against the front-end must not pay
        probe_timeout per sick replica; the probe loop already did).
        Non-READY seats derive from the state machine: STARTING is
        alive-but-compiling, DRAINING/DEAD are not routable."""
        with self._lock:
            if self.state == READY:
                return {
                    "state": READY,
                    "live": bool(self.last_health.get("live", True)),
                    "ready": bool(self.last_health.get("ready", True)),
                }
            return {
                "state": self.state,
                "live": self.state == STARTING,
                "ready": False,
            }


def _normalize_l2_spec(prefix_l2) -> dict | None:
    """Canonical ``{"mode", "capacity_bytes", "lookup_timeout_s"}`` for
    the fleet's ``prefix_l2=`` argument, or None (off)."""
    if prefix_l2 is None:
        return None
    spec = {
        "mode": "inproc",
        "capacity_bytes": 256 << 20,
        "lookup_timeout_s": 0.05,
    }
    if isinstance(prefix_l2, str):
        spec["mode"] = prefix_l2
    elif isinstance(prefix_l2, dict):
        spec.update(prefix_l2)
    else:
        raise ValueError(
            f"prefix_l2 must be None, 'inproc', 'spawn', or a dict; "
            f"got {prefix_l2!r}"
        )
    if spec["mode"] not in ("inproc", "spawn"):
        raise ValueError(
            f"prefix_l2 mode must be 'inproc' or 'spawn', got "
            f"{spec['mode']!r}"
        )
    if int(spec["capacity_bytes"]) < 1:
        raise ValueError(
            f"prefix_l2 capacity_bytes must be >= 1, got "
            f"{spec['capacity_bytes']}"
        )
    return spec


class ServingFleet:
    """N replica seats + the health/supervision plane over them.

    Exactly one of ``factory`` (in-process engines) or ``spawn_argv``
    (``serve_model`` subprocess children) selects the replica kind.
    The probe loop runs every ``probe_interval`` seconds;
    ``miss_limit`` consecutive failed probes (or one watchdog-fire
    delta) flip a replica to DRAINING and trigger a respawn, retried
    up to ``max_respawns`` times per seat before the seat goes DEAD.
    """

    def __init__(
        self,
        factory=None,
        *,
        spawn_argv: list[str] | None = None,
        replicas: int = 2,
        probe_interval: float = 1.0,
        miss_limit: int = 3,
        warmup: bool = True,
        respawn: bool = True,
        max_respawns: int = 8,
        respawn_backoff_s: float = 0.5,
        drain_timeout: float = 30.0,
        wait_ready: bool = True,
        start_timeout: float = 600.0,
        registry: obs_registry.Registry | None = None,
        spawn_kwargs: dict | None = None,
        prefix_l2=None,
    ):
        if (factory is None) == (spawn_argv is None):
            raise ValueError(
                "exactly one of factory= (in-process) or spawn_argv= "
                "(subprocess) selects the replica kind"
            )
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self._factory = factory
        self._spawn_argv = spawn_argv
        self._spawn_kwargs = dict(spawn_kwargs or {})
        self._warmup = bool(warmup)
        self.probe_interval = max(0.05, float(probe_interval))
        self.miss_limit = max(1, int(miss_limit))
        self._respawn = bool(respawn)
        self.max_respawns = int(max_respawns)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.drain_timeout = float(drain_timeout)
        self._lock = threading.Lock()
        self._closed = False  # guarded-by: self._lock
        self._draining = False  # guarded-by: self._lock
        self._stop = threading.Event()
        # set at the END of __init__; close() must tolerate being
        # called from a cold-start failure before it exists
        self._probe_thread: threading.Thread | None = None
        # the router registers itself here to be told when a seat's
        # engine is replaced (its affinity/load state for it is stale)
        self.listener = None
        # a rollout controller registers itself here: called with
        # (rid, handle) after a respawned replica passes readiness but
        # BEFORE it is installed/routable, so a seat respawned
        # mid-rollout rejoins at the fleet's target weights version
        # instead of resurrecting the boot checkpoint
        self.rollout_hook = None
        # shared secret for subprocess children's /admin/reload —
        # generated per fleet, injected into each child's env at spawn
        self.admin_token: str | None = None
        if spawn_argv is not None:
            token = self._spawn_kwargs.pop("admin_token", None)
            if token is None:
                import secrets

                token = secrets.token_hex(16)
            self.admin_token = token

        # -- fleet-global prefix L2 (cachetier) ------------------------
        # prefix_l2: None (off), "inproc" (one shared in-process
        # CacheTier — the InProcessReplica spelling), "spawn" (a
        # supervised cachetier daemon subprocess — survives nothing,
        # needs to survive nothing: clients degrade to L1-only on any
        # outage), or a dict with {"mode", "capacity_bytes",
        # "lookup_timeout_s"} overrides.
        self._l2_spec = _normalize_l2_spec(prefix_l2)
        self.cache_tier = None  # inproc mode: the shared store
        self.cachetier_address: str | None = None  # spawn mode: host:port
        self._cache_lock = threading.Lock()
        self._cache_proc = None  # guarded-by: self._cache_lock
        self._cache_respawns = 0  # guarded-by: self._cache_lock
        self._cache_admin = None  # invalidate/stats client (fleet-owned)
        if self._l2_spec is not None:
            self._start_prefix_l2()
            if self._factory is not None:
                self._factory = self._wrap_factory_with_l2(self._factory)
            elif self.cachetier_address is not None:
                # subprocess replicas learn the daemon address via the
                # serve_model flag; each child builds its own CacheClient
                self._spawn_argv = list(self._spawn_argv) + [
                    "--cachetier-l2", self.cachetier_address,
                ]

        self.metrics = (
            registry if registry is not None else obs_registry.Registry()
        )
        self._g_state = self.metrics.gauge(
            "fleet_replica_state",
            "replica lifecycle state (1 for the current state)",
        )
        self._m_respawns = self.metrics.counter(
            "fleet_respawns_total", "replica respawn attempts, by outcome"
        )
        self._m_probe_misses = self.metrics.counter(
            "fleet_probe_misses_total", "failed replica health probes"
        )

        # seat map: built once, never mutated (seats are stable; only
        # the state BEHIND a seat changes, under that seat's lock)
        self._slots: dict[int, _ReplicaSlot] = {
            rid: _ReplicaSlot(rid, self._new_handle(rid))
            for rid in range(int(replicas))
        }
        for rid in self._slots:
            self._g_state.set(1, replica=str(rid), state=STARTING)

        # parallel spawn: replicas start independently (one slow
        # compile must not serialize the fleet's cold start)
        errors: dict[int, BaseException] = {}

        def _boot(slot: _ReplicaSlot) -> None:
            try:
                with slot._lock:
                    handle = slot.handle
                handle.start()
                self._await_readiness(handle)
            except BaseException as e:  # noqa: BLE001 - per-seat verdict
                errors[slot.rid] = e
                # the seat enters the ORDINARY respawn path regardless
                # of wait_ready — a stranded STARTING seat that nobody
                # supervises would silently halve the fleet forever
                logger.warning(
                    "replica %d failed cold start: %s", slot.rid, e
                )
                self._flip_draining(slot, f"cold start failed: {e}")
                return
            # same install-vs-close ordering as _respawn_seat: close()
            # flips _closed before sweeping, so either we see it here
            # (and retire the fresh engine ourselves) or the sweep
            # runs after us and collects it
            with slot._lock:
                installed = not self.closed
                if installed:
                    slot.state = READY
            if not installed:
                handle.kill()
                return
            self._set_state_gauge(slot.rid, STARTING, READY)

        boot_threads = [
            threading.Thread(target=_boot, args=(s,), daemon=True)
            for s in self._slots.values()
        ]
        for t in boot_threads:
            t.start()
        if wait_ready:
            deadline = time.monotonic() + float(start_timeout)
            for t in boot_threads:
                t.join(max(0.1, deadline - time.monotonic()))
            if errors and len(errors) == len(self._slots):
                # nothing came up: fail construction with the root
                # cause (close() also stops the respawn threads)
                self.close()
                raise next(iter(errors.values()))
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="fleet-probe"
        )
        self._probe_thread.start()

    # -- construction helpers ------------------------------------------

    def _new_handle(self, rid: int):
        if self._factory is not None:
            return InProcessReplica(
                rid, self._factory, warmup=self._warmup
            )
        return SubprocessReplica(
            rid,
            self._spawn_argv,
            admin_token=self.admin_token,
            **self._spawn_kwargs,
        )

    # -- prefix L2 plumbing (cachetier) --------------------------------

    def _start_prefix_l2(self) -> None:
        from tensorflowonspark_tpu.cachetier import CacheTier, LocalClient

        spec = self._l2_spec
        if spec["mode"] == "inproc":
            self.cache_tier = CacheTier(
                capacity_bytes=spec["capacity_bytes"]
            )
            self._cache_admin = LocalClient(self.cache_tier)
            return
        self._spawn_cache_daemon(port=0)
        from tensorflowonspark_tpu.cachetier import CacheClient

        self._cache_admin = CacheClient(self.cachetier_address)
        threading.Thread(
            target=self._cache_supervise_loop,
            daemon=True,
            name="fleet-cachetier-supervise",
        ).start()

    def _spawn_cache_daemon(self, port: int) -> None:
        """Spawn the cachetier daemon and wait out its port-file barrier.
        Respawns pass the ORIGINAL bound port so every client's cached
        address stays valid across a daemon death."""
        pf = tempfile.mktemp(prefix="tfos-cachetier-port-")
        argv = [
            sys.executable,
            "-m",
            "tensorflowonspark_tpu.cachetier.service",
            "--port", str(port),
            "--port-file", pf,
            "--capacity-bytes", str(self._l2_spec["capacity_bytes"]),
        ]
        proc = subprocess.Popen(argv)
        deadline = time.monotonic() + 30.0
        try:
            while not os.path.exists(pf):
                if proc.poll() is not None:
                    raise RuntimeError(
                        "cachetier daemon exited during startup "
                        f"(rc={proc.returncode})"
                    )
                if time.monotonic() > deadline:
                    proc.kill()
                    raise TimeoutError(
                        "cachetier daemon did not publish its port "
                        "within 30s"
                    )
                time.sleep(0.02)
            with open(pf) as f:
                bound = int(f.read().strip())
        finally:
            try:
                os.unlink(pf)
            except OSError:
                pass
        with self._cache_lock:
            self._cache_proc = proc
        self.cachetier_address = f"127.0.0.1:{bound}"
        flightrec.note("cachetier_spawn", address=self.cachetier_address)

    def _cache_supervise_loop(self) -> None:
        """Respawn a dead cachetier daemon (warm state is lost — that
        is fine, it is a CACHE). While it is down, every client is
        already degrading to L1-only misses; nothing here is urgent or
        load-bearing, so failures just log and retry next round."""
        while not self._stop.wait(self.probe_interval):
            with self._cache_lock:
                proc = self._cache_proc
                respawns = self._cache_respawns
            if proc is None or proc.poll() is None:
                continue
            if respawns >= self.max_respawns:
                logger.error(
                    "cachetier daemon dead and respawn budget (%d) "
                    "spent; fleet continues L1-only",
                    self.max_respawns,
                )
                return
            with self._cache_lock:
                self._cache_respawns += 1
            port = int(self.cachetier_address.rpartition(":")[2])
            try:
                self._spawn_cache_daemon(port=port)
                flightrec.note("cachetier_respawn", port=port)
                logger.warning(
                    "cachetier daemon respawned on port %d", port
                )
            except Exception:  # noqa: BLE001 - retry next round
                logger.warning(
                    "cachetier daemon respawn failed", exc_info=True
                )

    def _new_l2(self, chunk: int):
        """One PrefixL2 facade for one replica (own filler thread; the
        underlying store/daemon is fleet-shared)."""
        from tensorflowonspark_tpu.cachetier import (
            CacheClient,
            LocalClient,
            PrefixL2,
        )

        spec = self._l2_spec
        if spec["mode"] == "inproc":
            client, own = LocalClient(self.cache_tier), False
        else:
            client, own = CacheClient(self.cachetier_address), True
        return PrefixL2(
            client,
            chunk=chunk,
            lookup_timeout_s=spec["lookup_timeout_s"],
            own_client=own,
        )

    def _wrap_factory_with_l2(self, inner):
        """Attach a fresh PrefixL2 to every factory-built engine
        (including respawns). Attach failure degrades to L1-only —
        never blocks a replica from serving."""

        def factory(*a, **kw):
            eng = inner(*a, **kw)
            try:
                chunk = getattr(eng, "_prefill_chunk", None)
                has_l1 = getattr(eng, "_prefix_store", None) is not None
                if chunk and has_l1 and hasattr(eng, "attach_prefix_l2"):
                    eng.attach_prefix_l2(self._new_l2(int(chunk)))
                else:
                    logger.warning(
                        "prefix_l2 configured but the engine has no "
                        "prefix cache (prefix_cache/prefill_chunk "
                        "unset); replica continues without L2"
                    )
            except Exception:  # noqa: BLE001 - L2 is optional
                logger.warning("prefix L2 attach failed", exc_info=True)
            return eng

        return factory

    def invalidate_prefix_version(self, version) -> int:
        """Drop one weights version's prefix entries from the fleet L2
        (the rollout reclamation hook) — exact by key construction;
        returns entries dropped (0 when no L2 / service down: harmless,
        the old version's keys can never be looked up again)."""
        if self._cache_admin is None:
            return 0
        from tensorflowonspark_tpu.cachetier import prefix as _prefix

        try:
            n = self._cache_admin.invalidate(
                _prefix.NS, _prefix.version_prefix(version)
            )
        except Exception:  # noqa: BLE001 - reclamation is best-effort
            logger.warning("prefix L2 invalidate failed", exc_info=True)
            return 0
        if n:
            flightrec.note(
                "cachetier_invalidate", version=str(version), dropped=n
            )
        return n

    def cache_stats(self) -> dict | None:
        """The shared cache tier's counters (None when no L2 is
        configured or the daemon is unreachable)."""
        if self._cache_admin is None:
            return None
        try:
            return self._cache_admin.stats()
        except Exception:  # noqa: BLE001 - stats are best-effort
            return None

    def _await_readiness(self, handle, timeout: float = 120.0) -> None:
        """The rejoin gate: a (re)spawned replica joins the routable
        set only once its OWN health says ready (warmup complete) — a
        compiling replica that "exists" is not a replica."""
        deadline = time.monotonic() + timeout
        while True:
            h = handle.health()
            if h.get("ready"):
                return
            if not h.get("live", True):
                raise ReplicaGone(
                    f"replica {handle.rid} died before readiness"
                )
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {handle.rid} not ready within {timeout}s"
                )
            time.sleep(0.05)

    def _set_state_gauge(self, rid: int, old: str, new: str) -> None:
        if old != new:
            self._g_state.remove(replica=str(rid), state=old)
        self._g_state.set(1, replica=str(rid), state=new)

    # -- snapshots (router + /stats surface) ---------------------------

    def views(self) -> list[dict]:
        return [s.view() for s in self._slots.values()]

    def ready_views(self) -> list[dict]:
        return [v for v in self.views() if v["state"] == READY]

    @property
    def draining(self) -> bool:
        return self._draining  # lint: lockfree-read: advisory one-bool admission flag; a stale read only delays one shed by a poll

    @property
    def closed(self) -> bool:
        return self._closed  # lint: lockfree-read: advisory one-bool flag, same as draining

    def states(self) -> dict[int, str]:
        return {rid: s.view()["state"] for rid, s in self._slots.items()}

    def health(self) -> dict:
        """Fleet-aggregated liveness/readiness + the per-replica split
        (the ``/healthz`` body in fleet mode). Served from the probe
        loop's CACHED verdicts (freshness = one ``probe_interval``):
        a front-end health check must answer fast even when a replica
        is hung — re-probing N replicas serially per call would make
        the aggregate /healthz flap exactly when one replica is sick."""
        per = {
            str(rid): s.health_view()
            for rid, s in self._slots.items()
        }
        draining = self.draining
        return {
            "live": any(h["live"] for h in per.values()),
            "ready": (
                not draining
                and any(
                    h["ready"] and h["state"] == READY
                    for h in per.values()
                )
            ),
            "draining": draining,
            "replicas": per,
        }

    def stats(self) -> dict:
        seats = {
            str(rid): s.seat_info() for rid, s in self._slots.items()
        }
        return {
            "replicas": len(seats),
            "ready": sum(
                1 for s in seats.values() if s["state"] == READY
            ),
            "draining": self.draining,
            "seats": seats,
        }

    # -- probe loop ----------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            try:
                self.probe_now()
            except Exception:  # pragma: no cover - probe_now guards
                logger.exception("fleet probe round failed")

    def probe_now(self) -> None:
        """One health round over every seat (also callable from tests
        for a deterministic refresh). READY seats accumulate misses /
        watchdog deltas here; STARTING and DRAINING seats belong to
        their spawn/respawn threads and are left alone."""
        for slot in self._slots.values():
            view = slot.view()
            if view["state"] != READY:
                continue
            ok = True
            h: dict = {"live": False, "ready": False}
            answered = False  # the replica POSITIVELY answered live
            st: dict = {}
            try:
                failpoint("fleet.replica_probe")
                h = view["handle"].health()
                if h.get("live"):
                    answered = True
                    st = view["handle"].stats()
                else:
                    ok = False
            except Exception:  # noqa: BLE001 - a failed probe is a miss
                ok = False
                h = {"live": False, "ready": False}
            if ok and not h.get("ready"):
                # alive but no longer ready (engine closed under us, or
                # warmup regressed — neither is routable)
                ok = False
            reason = None
            with slot._lock:
                if slot.state != READY:
                    continue
                if slot.generation != view["generation"]:
                    continue  # respawned under us; stale verdict
                if answered:
                    # only a POSITIVE verdict replaces the cached
                    # health: a single unanswered probe (a GC pause, a
                    # long compile) below miss_limit must not flap the
                    # reported /healthz to dead while the replica is
                    # still serving — the drain threshold IS the
                    # debounce, and reaching it flips the seat out of
                    # READY anyway
                    slot.last_health = dict(h)
                if not ok:
                    slot.misses += 1
                    misses = slot.misses
                    if misses >= self.miss_limit:
                        reason = (
                            f"missed {misses} probes "
                            f"(interval {self.probe_interval}s)"
                        )
                else:
                    misses = 0
                    slot.misses = 0
                    slot.last_stats = st
                    fires = int(st.get("watchdog_fires") or 0)
                    if fires > slot.last_watchdog:
                        reason = (
                            f"engine watchdog fired ({fires} total) — "
                            "EngineWedged"
                        )
                    slot.last_watchdog = fires
            if not ok:
                self._m_probe_misses.inc(replica=str(slot.rid))
            if reason is not None:
                self._flip_draining(
                    slot, reason, generation=view["generation"]
                )

    # -- failure handling / supervision --------------------------------

    def report_failure(
        self, rid: int, reason: str, generation: int | None = None
    ) -> None:
        """Request-path verdict from the router: a dispatch came back
        ``EngineWedged``/:class:`ReplicaGone`. Flips the replica to
        DRAINING and respawns — faster than waiting out the probe
        interval, and the router has already rerouted the request.
        ``generation`` scopes the verdict: a stale failure from a
        replica's OLD engine must not drain the freshly respawned one
        behind the same seat."""
        slot = self._slots.get(int(rid))
        if slot is not None:
            self._flip_draining(
                slot, f"request path: {reason}", generation=generation
            )

    def _flip_draining(
        self,
        slot: _ReplicaSlot,
        reason: str,
        generation: int | None = None,
    ) -> None:
        if self.closed:
            return
        with slot._lock:
            if slot.state in (DRAINING, DEAD):
                return
            if generation is not None and slot.generation != generation:
                return  # verdict about a generation already replaced
            old = slot.state
            slot.state = DRAINING
            slot.last_reason = reason
            gen = slot.generation
        self._set_state_gauge(slot.rid, old, DRAINING)
        logger.warning(
            "replica %d -> draining (%s)", slot.rid, reason
        )
        flightrec.note(
            "replica_drain", replica=slot.rid, reason=reason,
            generation=gen,
        )
        # off-thread: _flip_draining runs on the REQUEST path (the
        # router reports failures before retrying), and the dump's
        # file IO must not sit under the failover it races
        threading.Thread(
            target=flightrec.dump_now,
            args=(f"replica_drain:{slot.rid}",),
            daemon=True,
        ).start()
        threading.Thread(
            target=self._respawn_seat,
            args=(slot, reason),
            daemon=True,
            name=f"fleet-respawn-{slot.rid}",
        ).start()

    def _respawn_seat(self, slot: _ReplicaSlot, reason: str) -> None:
        """Drain the seat's old engine, then (optionally) respawn a
        fresh one, rejoin gated on readiness. Runs on its own daemon
        thread — supervision must not block the probe loop."""
        with slot._lock:
            old_handle = slot.handle
        try:
            # in-flight work runs out (or was already aborted by the
            # watchdog / died with the process) before the seat flips
            old_handle.terminate(drain=True, timeout=self.drain_timeout)
        except Exception:  # noqa: BLE001 - a dead handle drains itself
            logger.exception("replica %d drain failed", slot.rid)
        if not self._respawn or self.closed:
            self._mark_dead(slot, f"respawn disabled ({reason})")
            return
        attempts = 0
        while not self.closed:
            attempts += 1
            with slot._lock:
                budget_spent = slot.spawn_failures >= self.max_respawns
                if not budget_spent:
                    slot.respawns += 1
                    slot.generation += 1
                    slot.state = STARTING
                    slot.misses = 0
                    slot.last_watchdog = 0
                    slot.last_stats = {}
                    # the fresh engine starts with a clean verdict —
                    # the dead generation's cached {live: False} must
                    # not gate the respawned seat's readiness until
                    # the next probe round
                    slot.last_health = {"live": True, "ready": True}
                    gen = slot.generation
            if budget_spent:
                self._mark_dead(
                    slot, f"respawn budget ({self.max_respawns}) spent"
                )
                return
            self._set_state_gauge(slot.rid, DRAINING, STARTING)
            handle = self._new_handle(slot.rid)
            try:
                handle.start()
                self._await_readiness(handle)
                hook = self.rollout_hook
                if hook is not None:
                    # mid-rollout respawn: bring the fresh replica (it
                    # boots on the ORIGINAL checkpoint) to the fleet's
                    # current target weights BEFORE it becomes routable
                    try:
                        hook(slot.rid, handle)
                    except Exception:  # noqa: BLE001 - rejoin anyway
                        logger.exception(
                            "replica %d rollout re-sync failed; seat "
                            "rejoins on its boot weights",
                            slot.rid,
                        )
            except Exception as e:  # noqa: BLE001 - retried with backoff
                self._m_respawns.inc(outcome="failed")
                logger.warning(
                    "replica %d respawn attempt %d failed: %s",
                    slot.rid,
                    attempts,
                    e,
                )
                try:
                    handle.kill()
                except Exception:  # noqa: BLE001 - best-effort cleanup
                    pass
                with slot._lock:
                    slot.state = DRAINING
                    slot.spawn_failures += 1
                self._set_state_gauge(slot.rid, STARTING, DRAINING)
                time.sleep(self.respawn_backoff_s * attempts)
                continue
            # install-vs-close ordering: close() flips _closed BEFORE
            # sweeping the seats, so checking it inside the seat lock
            # means either we see closed (no install) or close()'s
            # sweep runs after us and collects THIS handle — a fresh
            # replica can never leak past close() either way
            with slot._lock:
                installed = not self.closed
                if installed:
                    slot.handle = handle
                    slot.state = READY
                    slot.spawn_failures = 0
            if not installed:
                handle.kill()
                return
            self._set_state_gauge(slot.rid, STARTING, READY)
            self._m_respawns.inc(outcome="ok")
            listener = self.listener
            if listener is not None:
                # the new engine is COLD: affinity/load learned about
                # the old one is stale
                listener.replica_reset(slot.rid)
            flightrec.note(
                "replica_respawn", replica=slot.rid, generation=gen,
                reason=reason,
            )
            flightrec.dump_now(f"replica_respawn:{slot.rid}")
            logger.info(
                "replica %d respawned (generation %d)", slot.rid, gen
            )
            return
        self._mark_dead(slot, "fleet closed during respawn")

    def _mark_dead(self, slot: _ReplicaSlot, reason: str) -> None:
        with slot._lock:
            old = slot.state
            if old == DEAD:
                return
            slot.state = DEAD
            slot.last_reason = reason
        self._set_state_gauge(slot.rid, old, DEAD)
        flightrec.note("replica_dead", replica=slot.rid, reason=reason)
        logger.error("replica %d is DEAD: %s", slot.rid, reason)

    # -- rollout seat holds (serving/rollout.py drives these) ----------

    def hold_seat(self, rid: int, reason: str = "rollout") -> None:
        """Flip a READY seat to DRAINING **without** scheduling a
        respawn — the caller (a rollout controller) owns the seat until
        :meth:`release_seat` or :meth:`force_respawn`. The router stops
        placing new load the moment the state flips; in-flight requests
        keep running on the handle (drain by polling
        ``handle.unresolved()``)."""
        slot = self._slots[int(rid)]
        with slot._lock:
            if slot.state != READY:
                raise RuntimeError(
                    f"replica {rid} is {slot.state}, not ready"
                )
            slot.state = DRAINING
            slot.hold = True
            slot.last_reason = reason
            gen = slot.generation
        self._set_state_gauge(slot.rid, READY, DRAINING)
        flightrec.note(
            "replica_drain", replica=slot.rid, reason=reason,
            generation=gen, hold=True,
        )

    def release_seat(self, rid: int) -> None:
        """Return a held seat to the routable set — the rejoin gate.
        Callers verify readiness FIRST (the rollout controller gates on
        the replica's own ``/readyz``-equivalent health); a fresh clean
        verdict is installed so a stale cached probe cannot shadow-fail
        the rejoined seat until the next round."""
        slot = self._slots[int(rid)]
        with slot._lock:
            if not slot.hold:
                raise RuntimeError(f"replica {rid} is not held")
            slot.hold = False
            if self.closed:
                return  # close() already swept the seat
            slot.state = READY
            slot.misses = 0
            slot.last_health = {"live": True, "ready": True}
        self._set_state_gauge(slot.rid, DRAINING, READY)

    def force_respawn(self, rid: int, reason: str) -> None:
        """Last-resort seat recovery for a holder whose restore failed
        (e.g. rollback could not re-install the prior weights): clear
        the hold and run the ordinary respawn path — a FRESH replica
        from the factory/spawn argv, serving its boot weights."""
        slot = self._slots[int(rid)]
        with slot._lock:
            slot.hold = False
            if slot.state == DEAD:
                return
            old = slot.state if slot.state != DRAINING else None
            slot.state = DRAINING
            slot.last_reason = reason
        if old is not None:
            self._set_state_gauge(slot.rid, old, DRAINING)
        flightrec.note("replica_drain", replica=slot.rid, reason=reason)
        threading.Thread(
            target=self._respawn_seat,
            args=(slot, reason),
            daemon=True,
            name=f"fleet-respawn-{slot.rid}",
        ).start()

    # -- drain / shutdown ----------------------------------------------

    def begin_drain(self) -> None:
        """Full-fleet drain: the router sheds every new request with
        503 (``FleetUnavailable``) while accepted work runs out —
        the rolling-restart front half."""
        with self._lock:
            self._draining = True
        flightrec.note("fleet_drain")

    def close(self, drain: bool = False, timeout: float = 60.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._draining = True
        self._stop.set()
        handles = []
        for slot in self._slots.values():
            with slot._lock:
                old = slot.state
                slot.state = DRAINING
                handles.append((slot.rid, old, slot.handle))
        for rid, old, h in handles:
            self._set_state_gauge(rid, old, DRAINING)
            try:
                h.terminate(drain=drain, timeout=timeout)
            except Exception:  # noqa: BLE001 - teardown is best-effort
                logger.exception("replica %s teardown failed", rid)
        if self._probe_thread is not None and self._probe_thread.is_alive():
            self._probe_thread.join(timeout=self.probe_interval + 5.0)
        # cache tier teardown AFTER the replicas: their engines' close
        # paths may still flush L2 offers, all of which tolerate a dead
        # service anyway
        with self._cache_lock:
            proc, self._cache_proc = self._cache_proc, None
        if proc is not None and proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
        if self._cache_admin is not None:
            try:
                self._cache_admin.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
