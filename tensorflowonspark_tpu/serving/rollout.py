"""Zero-downtime weight rollout: health-gated rolling hot-swap with
automatic rollback across the serving fleet.

The missing piece of ROADMAP item 5 ("train and serve concurrently,
nothing restarts"): training publishes a checkpoint, live engines pick
it up without restarting or dropping a request. Three layers:

- **Publication channel** — a directory holding an atomically-written
  ``LATEST`` pointer (tmp + ``os.replace``, body CRC) naming an orbax
  checkpoint directory plus a version label. :func:`read_latest`
  REJECTS torn pointers (bad JSON / CRC mismatch) and partial or
  in-progress checkpoints (``compute.checkpoint.checkpoint_complete``)
  — a watcher can never hot-swap half a write into a serving fleet.
  In-process sources (tests, a co-located trainer) skip the filesystem
  entirely via :meth:`RolloutController.publish`.

- **Rolling hot-swap** — :class:`RolloutController` rolls a new
  version across the fleet **one replica at a time under router
  health**: hold the seat (READY→DRAINING, no new load, no respawn),
  wait for in-flight quiescence (the PR-13 ``unresolved()`` path),
  swap the param tree between decode blocks
  (``ContinuousBatcher.swap_weights`` for in-process replicas — LoRA
  adapter-only swaps move just the factors; ``SubprocessReplica
  .reload`` → the child's authenticated ``/admin/reload`` otherwise),
  re-warm, then gate rejoin on the replica's own readiness before
  touching the next seat. The fleet serves MIXED versions mid-rollout
  by design: every completion is stamped with the weights version it
  resolved under, per-replica versions ride the
  ``fleet_weights_version`` gauge, and the router's affinity entries
  for a swapped replica are dropped (``replica_reset``) together with
  the engine's own prefix cache — post-swap placement can never reach
  stale prefill state.

- **Automatic rollback** — a failed checkpoint load, a
  :class:`~tensorflowonspark_tpu.serving.engine.WeightsIncompatible`
  shape/layout mismatch, a failed warmup probe, or a health regression
  after the swap rolls every already-swapped replica back to its
  **retained per-seat prior** (for in-process seats a reference to the
  previous tree — free; for subprocess seats the previously applied
  published path, or a respawn back to the boot checkpoint when none
  exists). The fleet ends every rollout in a coherent serving state:
  ``completed`` or ``rolled_back``, never a mixed wedge. A replica
  respawned MID-rollout (SIGKILL chaos) re-syncs to the fleet's
  current target version through ``ServingFleet.rollout_hook`` before
  it becomes routable.

Failpoints: ``rollout.publish`` (channel write; "drop" = lost
publication — bounded staleness, never corruption), ``rollout.swap``
(before each seat), ``rollout.verify`` (post-swap verification; a
raise = health regression → rollback).

Obs: ``fleet_weights_version{replica}`` gauge (value = the version's
monotonic ordinal), ``rollout_swap_seconds`` histogram,
``rollout_total{outcome=completed|rolled_back|failed}`` counter;
flightrec ``rollout_begin`` / ``replica_swap`` / ``rollout_rollback``
(dumped on rollback — the incident a postmortem reads).

Operator docs: docs/SERVING.md "Rolling weight updates";
docs/ROBUSTNESS.md has the rollout/rollback decision table.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import zlib

from tensorflowonspark_tpu.cluster import wire
from tensorflowonspark_tpu.obs import flightrec, reqtrace
from tensorflowonspark_tpu.obs import registry as obs_registry
from tensorflowonspark_tpu.serving.engine import WeightsIncompatible
from tensorflowonspark_tpu.serving.fleet import READY
from tensorflowonspark_tpu.utils.failpoints import failpoint

logger = logging.getLogger(__name__)

__all__ = [
    "MANIFEST_NAME",
    "RolloutController",
    "WeightsUpdate",
    "checkpoint_loader",
    "lora_state",
    "publish_checkpoint",
    "publish_params",
    "read_latest",
]

MANIFEST_NAME = "LATEST"


@dataclasses.dataclass(frozen=True)
class WeightsUpdate:
    """One publishable weights version. ``params`` is the in-process
    payload (a pytree for ``kind='full'``, a :func:`lora_state` factor
    mapping for ``kind='lora'``) and never crosses a process boundary;
    ``path`` names a committed orbax checkpoint directory that
    subprocess replicas (and path-only in-process loaders) read."""

    version: str
    kind: str = "full"  # 'full' | 'lora'
    path: str | None = None
    step: int | None = None
    params: object = dataclasses.field(default=None, repr=False)

    def __post_init__(self):
        if self.kind not in ("full", "lora"):
            raise ValueError(
                f"kind must be 'full' or 'lora', got {self.kind!r}"
            )
        if self.params is None and self.path is None:
            raise ValueError(
                "a WeightsUpdate needs params= (in-process) and/or "
                "path= (a published checkpoint directory)"
            )


# ---------------------------------------------------------------------------
# the publication channel
# ---------------------------------------------------------------------------


def _manifest_body(manifest: dict) -> bytes:
    return json.dumps(manifest, sort_keys=True).encode("utf-8")


def publish_checkpoint(
    channel_dir: str,
    *,
    version: str,
    path: str,
    kind: str = "full",
    step: int | None = None,
) -> dict:
    """Atomically point the channel's ``LATEST`` at a committed
    checkpoint directory. Write order is tmp + ``os.replace`` so a
    reader never sees a torn pointer on posix; the body additionally
    carries its own CRC so a reader on a filesystem without rename
    atomicity (or a partially copied channel) still rejects torn
    content instead of loading garbage. Publish AFTER the checkpoint
    itself is fully written (``CheckpointManager.wait()`` for async
    saves) — :func:`read_latest` independently refuses incomplete
    checkpoint directories."""
    manifest = wire.encode(
        "rollout.manifest",
        version=str(version),
        kind=str(kind),
        path=os.path.abspath(path) if "://" not in path else path,
        step=None if step is None else int(step),
    )
    if failpoint("rollout.publish") == "drop":
        # a LOST publication: watchers simply keep serving the prior
        # version until the next publish — staleness, never corruption
        logger.warning(
            "rollout.publish dropped (failpoint): %s not published",
            manifest["version"],
        )
        return manifest
    body = _manifest_body(manifest)
    record = json.dumps(
        wire.encode(
            "rollout.latest", crc=zlib.crc32(body), manifest=manifest
        )
    )
    os.makedirs(channel_dir, exist_ok=True)
    tmp = os.path.join(
        channel_dir, f".{MANIFEST_NAME}.tmp.{os.getpid()}"
    )
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(record + "\n")
    os.replace(tmp, os.path.join(channel_dir, MANIFEST_NAME))
    return manifest


def publish_params(
    channel_dir: str,
    params,
    *,
    version: str,
    kind: str = "full",
    step: int | None = None,
) -> WeightsUpdate:
    """Write ``params`` (a full tree, or a :func:`lora_state` factor
    mapping for ``kind='lora'``) as an orbax checkpoint under the
    channel and publish the pointer — the one-call path for a trainer
    (or a test/bench harness) shipping a version to a fleet whose
    replicas live in other processes."""
    from tensorflowonspark_tpu.compute.checkpoint import save_checkpoint

    path = os.path.join(channel_dir, "versions", str(version))
    save_checkpoint(path, params)
    publish_checkpoint(
        channel_dir, version=version, path=path, kind=kind, step=step
    )
    return WeightsUpdate(
        version=str(version), kind=kind, path=path, step=step,
        params=params,
    )


def read_latest(channel_dir: str) -> WeightsUpdate | None:
    """The channel's current publication, or ``None`` when there is
    nothing VALID to serve: no pointer yet, a torn/corrupt pointer
    (bad JSON, CRC mismatch, missing fields), or a pointer naming a
    missing/incomplete checkpoint directory. Rejection is silent by
    design — the watcher polls; a torn write is mid-publish, not an
    incident."""
    try:
        with open(
            os.path.join(channel_dir, MANIFEST_NAME), encoding="utf-8"
        ) as f:
            raw = f.read()
    except OSError:
        return None
    try:
        doc = wire.decode("rollout.latest", json.loads(raw))
        # CRC over the manifest AS WRITTEN (extras included) — a newer
        # add-only publisher's pointer still verifies on this reader.
        raw_manifest = doc["manifest"]
        if int(doc["crc"]) != zlib.crc32(_manifest_body(raw_manifest)):
            logger.warning(
                "rollout channel %s: LATEST pointer CRC mismatch "
                "(torn write) — ignored", channel_dir,
            )
            return None
        manifest = wire.decode("rollout.manifest", raw_manifest)
        version = str(manifest["version"])
        kind = str(manifest.get("kind") or "full")
        path = manifest.get("path")
        step = manifest.get("step")
    except (ValueError, KeyError, TypeError):
        logger.warning(
            "rollout channel %s: unparsable LATEST pointer — ignored",
            channel_dir,
        )
        return None
    if kind not in ("full", "lora") or not path:
        return None
    from tensorflowonspark_tpu.compute.checkpoint import (
        checkpoint_complete,
    )

    if not checkpoint_complete(path):
        logger.warning(
            "rollout channel %s: %s points at an incomplete checkpoint "
            "%s — ignored", channel_dir, version, path,
        )
        return None
    return WeightsUpdate(
        version=version, kind=kind, path=path,
        step=None if step is None else int(step),
    )


# ---------------------------------------------------------------------------
# payload helpers
# ---------------------------------------------------------------------------


def lora_state(params):
    """Extract the adapter-only update payload from a LoRA-ified tree:
    a nested mapping mirroring ``params`` down to each LoRA kernel,
    carrying just ``{"a", "b"}`` host arrays — the cheap payload
    ``swap_weights(kind='lora')`` grafts onto the resident bases.
    Returns ``None`` when the tree holds no LoRA kernels."""
    import numpy as np

    from tensorflowonspark_tpu.ops.lora import (
        LoraTensor,
        MultiLoraTensor,
    )

    def walk(node):
        if isinstance(node, (LoraTensor, MultiLoraTensor)):
            return {"a": np.asarray(node.a), "b": np.asarray(node.b)}
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                sub = walk(v)
                if sub is not None:
                    out[k] = sub
            return out or None
        return None

    return walk(params)


def checkpoint_loader(target_params):
    """Loader for path-published updates applied to IN-PROCESS
    replicas: restores a ``kind='full'`` checkpoint against
    ``target_params``'s structure (so restored arrays land on the
    running tree's shardings and a written-by-someone-else tree fails
    loudly instead of half-loading), and a ``kind='lora'`` factor
    checkpoint as a plain tree. Handles both ``save_checkpoint`` roots
    and ``CheckpointManager`` step directories (whose tree nests under
    the ``default`` item)."""

    def load(update: WeightsUpdate):
        from tensorflowonspark_tpu.compute.checkpoint import (
            restore_checkpoint,
        )

        path = update.path
        nested = os.path.join(path, "default")
        if os.path.isdir(nested):
            path = nested  # a CheckpointManager step dir
        if update.kind == "lora":
            return restore_checkpoint(path)
        try:
            return restore_checkpoint(path, target=target_params)
        except (ValueError, KeyError, TypeError) as e:
            # orbax's structure/shape rejection against the pinned
            # target: the published tree does not fit the running
            # config — the same incompatibility class a post-load
            # swap_weights would report (IO errors propagate as-is)
            raise WeightsIncompatible(
                f"published checkpoint {update.version!r} does not "
                f"fit the running weights: {e}"
            ) from e

    return load


# ---------------------------------------------------------------------------
# the rollout controller
# ---------------------------------------------------------------------------


class _SeatFailure(Exception):
    """Internal: one seat's swap failed. ``held`` = the seat is still
    held in DRAINING; ``swapped`` = the new weights may already be
    installed on it (restore required, not just release); ``prior`` =
    the retained prior captured under the hold (None when the seat was
    never held)."""

    def __init__(self, rid, stage, cause, held, swapped, prior=None):
        super().__init__(f"replica {rid} {stage}: {cause!r}")
        self.rid = rid
        self.stage = stage
        self.cause = cause
        self.held = held
        self.swapped = swapped
        self.prior = prior


class RolloutController:
    """Rolls published weight versions across a serving target.

    ``target`` is a :class:`~tensorflowonspark_tpu.serving.fleet
    .ServingFleet` (the real deployment shape: one replica at a time
    under router health) or a bare
    :class:`~tensorflowonspark_tpu.serving.engine.ContinuousBatcher`
    (single-engine ``serve_model``: swap in place between decode
    blocks, verify, roll back on failure).

    One rollout runs at a time (``_roll_lock``); :meth:`publish` and
    the channel watcher both funnel through :meth:`roll`.

    ``loader`` turns a path-published update into an in-process params
    payload (see :func:`checkpoint_loader`); subprocess replicas load
    their own path via ``/admin/reload`` and never need it.
    """

    def __init__(
        self,
        target,
        *,
        channel_dir: str | None = None,
        loader=None,
        poll_interval: float = 2.0,
        drain_timeout: float = 60.0,
        verify_timeout: float = 120.0,
        swap_timeout: float = 600.0,
        warmup_probe: bool = True,
        registry: obs_registry.Registry | None = None,
    ):
        if hasattr(target, "views") and hasattr(target, "hold_seat"):
            self._fleet = target
            self._engine = None
        elif hasattr(target, "swap_weights"):
            self._fleet = None
            self._engine = target
        else:
            raise TypeError(
                "target must be a ServingFleet or an engine with "
                f"swap_weights(), got {type(target).__name__}"
            )
        self._channel_dir = channel_dir
        self._loader = loader
        self._poll_interval = max(0.05, float(poll_interval))
        self._drain_timeout = float(drain_timeout)
        self._verify_timeout = float(verify_timeout)
        self._swap_timeout = float(swap_timeout)
        self._warmup_probe = bool(warmup_probe)

        # one rollout at a time; never nested with self._lock
        self._roll_lock = threading.Lock()
        self._lock = threading.Lock()
        self._applied: dict[int, WeightsUpdate] = {}  # guarded-by: self._lock
        self._target_update: WeightsUpdate | None = None  # guarded-by: self._lock
        self._ords: dict[str, int] = {}  # guarded-by: self._lock
        self._outcomes: dict[str, int] = {}  # guarded-by: self._lock
        # {"type": ..., "error": ...} of the most recent failed/rolled-
        # back rollout, None after a completed one — serve_model's
        # /admin/reload maps it onto HTTP status codes
        self._last_error: dict | None = None  # guarded-by: self._lock
        self._last_seen: str | None = None  # watcher thread only
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        reg = registry
        if reg is None:
            src = self._fleet if self._fleet is not None else self._engine
            reg = getattr(src, "metrics", None)
            if reg is None or not hasattr(reg, "gauge"):
                reg = obs_registry.Registry()
        self.metrics = reg
        self._g_version = reg.gauge(
            "fleet_weights_version",
            "serving weights version per replica (value = the "
            "version's monotonic publication ordinal)",
        )
        self._h_swap = reg.histogram(
            "rollout_swap_seconds",
            "per-replica hot-swap latency (drain wait excluded): "
            "load + install + re-warm + readiness gate",
        )
        self._m_total = reg.counter(
            "rollout_total", "rollouts by outcome"
        )

        if self._fleet is not None:
            self._fleet.rollout_hook = self._on_respawn
            for view in self._fleet.views():
                ver = self._handle_version(view["handle"])
                if ver is not None:
                    self._set_version_gauge(view["rid"], ver)
        else:
            self._set_version_gauge(0, self._engine.weights_version)

    # -- observability -------------------------------------------------

    @staticmethod
    def _handle_version(handle):
        try:
            return handle.health().get("weights_version")
        except Exception:  # noqa: BLE001 - a sick seat has no version
            return None

    def _set_version_gauge(self, rid: int, version: str) -> None:
        with self._lock:
            ordv = self._ords.setdefault(
                str(version), len(self._ords) + 1
            )
        self._g_version.set(ordv, replica=str(rid))

    def _record_applied(self, rid: int, update: WeightsUpdate) -> None:
        with self._lock:
            self._applied[rid] = update
            ordv = self._ords.setdefault(
                update.version, len(self._ords) + 1
            )
        self._g_version.set(ordv, replica=str(rid))

    def stats(self) -> dict:
        with self._lock:
            return {
                "target_version": (
                    None
                    if self._target_update is None
                    else self._target_update.version
                ),
                "applied": {
                    str(rid): u.version
                    for rid, u in sorted(self._applied.items())
                },
                "outcomes": dict(self._outcomes),
                "version_ordinals": dict(self._ords),
                "last_error": self._last_error,
            }

    def _count_outcome(self, outcome: str) -> None:
        self._m_total.inc(outcome=outcome)
        with self._lock:
            self._outcomes[outcome] = (
                self._outcomes.get(outcome, 0) + 1
            )

    @property
    def last_error(self) -> dict | None:
        with self._lock:
            return (
                None
                if self._last_error is None
                else dict(self._last_error)
            )

    def _note_error(self, cause: BaseException | None, stage: str) -> None:
        with self._lock:
            if cause is None:
                self._last_error = None
            else:
                self._last_error = {
                    "type": type(cause).__name__,
                    "error": str(cause),
                    "stage": stage,
                }

    # -- public API ----------------------------------------------------

    def publish(
        self,
        params=None,
        *,
        version: str,
        kind: str = "full",
        path: str | None = None,
        step: int | None = None,
    ) -> str:
        """In-process publication: roll ``params`` (and/or a published
        ``path`` for subprocess seats) across the target NOW,
        synchronously. Returns the rollout outcome
        (``completed`` / ``rolled_back`` / ``failed``)."""
        return self.roll(
            WeightsUpdate(
                version=str(version), kind=kind, path=path, step=step,
                params=params,
            )
        )

    def roll(self, update: WeightsUpdate) -> str:
        with self._roll_lock:
            return self._roll(update)

    def start(self) -> None:
        """Watch the publication channel; each NEW valid version rolls
        out on the watcher thread. A version that fails to roll is not
        retried until a different version (or a re-publish under a new
        label) appears — retry loops on a poisoned checkpoint would
        drain/re-warm the fleet forever."""
        if self._channel_dir is None:
            raise ValueError("start() requires channel_dir=")
        if self._thread is not None:
            return
        # restartable: a prior stop() left the event set and the
        # respawn hook deregistered
        self._stop.clear()
        if (
            self._fleet is not None
            and self._fleet.rollout_hook is None
        ):
            self._fleet.rollout_hook = self._on_respawn
        # seed with the channel's current content: the fleet just
        # booted from the newest checkpoint lineage; re-rolling the
        # same bytes at startup would churn every replica for nothing
        cur = read_latest(self._channel_dir)
        self._last_seen = None if cur is None else cur.version
        self._thread = threading.Thread(
            target=self._watch_loop, daemon=True, name="rollout-watch"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll_interval + 5.0)
            self._thread = None
        if (
            self._fleet is not None
            and self._fleet.rollout_hook == self._on_respawn
        ):
            self._fleet.rollout_hook = None

    def _watch_loop(self) -> None:
        while not self._stop.wait(self._poll_interval):
            try:
                upd = read_latest(self._channel_dir)
            except Exception:  # noqa: BLE001 - keep watching
                logger.exception("rollout channel read failed")
                continue
            if upd is None or upd.version == self._last_seen:
                continue
            self._last_seen = upd.version
            try:
                outcome = self.roll(upd)
                logger.info(
                    "rollout of %r: %s", upd.version, outcome
                )
            except Exception:  # noqa: BLE001 - keep watching
                logger.exception("rollout of %r crashed", upd.version)

    # -- the rolling swap ----------------------------------------------

    def _roll(self, update: WeightsUpdate) -> str:
        flightrec.note(
            "rollout_begin", version=update.version,
            swap_kind=update.kind,
        )
        logger.info(
            "rollout begin: version=%r kind=%s", update.version,
            update.kind,
        )
        if self._fleet is None:
            return self._roll_single(update)
        seats = sorted(
            (
                v
                for v in self._fleet.views()
                if v["state"] == READY
            ),
            key=lambda v: v["rid"],
        )
        if not seats:
            logger.error("rollout failed: no ready replica")
            self._note_error(RuntimeError("no ready replica"), "place")
            self._count_outcome("failed")
            return "failed"
        if update.path is None and any(
            self._seat_needs_path(v["handle"]) for v in seats
        ):
            # a pure configuration error: fail BEFORE any seat is
            # held/drained (half a fleet must not go through the
            # rollback/respawn machinery for an update that could
            # never have reached its subprocess children)
            err = WeightsIncompatible(
                "params-only update cannot reach subprocess replicas "
                "— publish it to disk (publish_params/"
                "publish_checkpoint) so the children can load a path"
            )
            logger.error(
                "rollout of %r failed: %s", update.version, err
            )
            self._note_error(err, "place")
            self._count_outcome("failed")
            return "failed"
        swapped: list[tuple[int, WeightsUpdate | None]] = []
        skipped: list[int] = []
        failure: _SeatFailure | None = None
        for view in seats:
            rid = view["rid"]
            try:
                failpoint("rollout.swap")
                prior = self._swap_seat(rid, update)
            except _SeatFailure as f:
                if f.stage == "hold":
                    # the seat left READY under us (probe drain, a
                    # SIGKILLed replica respawning): its supervisor
                    # owns it — skip, the respawn hook (and the
                    # straggler sweep below) re-syncs it. A dead seat
                    # must not roll back the healthy ones.
                    logger.warning(
                        "rollout of %r skipping replica %d: %s",
                        update.version, f.rid, f.cause,
                    )
                    skipped.append(f.rid)
                    continue
                failure = f
                break
            except BaseException as e:  # noqa: BLE001 - e.g. an armed
                # rollout.swap failpoint, or a loader crash before the
                # seat was ever touched
                failure = _SeatFailure(rid, "pre-swap", e, False, False)
                break
            swapped.append((rid, prior))
        if failure is None and not swapped:
            # nothing was actually rolled (every seat skipped away
            # mid-rollout) — that is a failure, not a completion
            err = RuntimeError(
                f"no replica could be swapped (skipped: {skipped})"
            )
            logger.error("rollout of %r failed: %s", update.version, err)
            self._note_error(err, "place")
            self._count_outcome("failed")
            return "failed"
        if failure is not None:
            f = failure
            logger.error(
                "rollout of %r failed at replica %d (%s): %r — "
                "rolling back %d swapped replica(s)",
                update.version, f.rid, f.stage, f.cause, len(swapped),
            )
            flightrec.note(
                "rollout_rollback", version=update.version,
                failed_replica=f.rid, stage=f.stage,
                error=repr(f.cause), swapped=[r for r, _ in swapped],
            )
            # the failed seat first (it may hold half-applied state),
            # then the successfully swapped seats newest-first
            self._restore_seat(
                f.rid, f.prior, held=f.held, swapped=f.swapped
            )
            for rid, pr in reversed(swapped):
                self._restore_seat(rid, pr, held=False, swapped=True)
            self._note_error(f.cause, f.stage)
            self._count_outcome("rolled_back")
            flightrec.dump_now(f"rollout_rollback:{update.version}")
            return "rolled_back"
        with self._lock:
            self._target_update = update
        # Convergence pass, ALWAYS: a seat that was skipped — or that
        # was respawning at rollout start and rejoined on its boot
        # weights before _target_update became visible to the respawn
        # hook — is swapped in place here (a no-op sweep when every
        # READY seat already reports the target version).
        self._sync_stragglers(update)
        self._reclaim_l2(update, swapped)
        self._note_error(None, "")
        self._count_outcome("completed")
        flightrec.note(
            "rollout_complete", version=update.version,
            replicas=[r for r, _ in swapped], skipped=skipped,
        )
        logger.info(
            "rollout of %r completed across %d replica(s)%s",
            update.version, len(swapped),
            f" ({len(skipped)} skipped to their respawn path)"
            if skipped
            else "",
        )
        return "completed"

    def _reclaim_l2(
        self,
        update: WeightsUpdate,
        swapped: list[tuple[int, WeightsUpdate | None]],
    ) -> None:
        """After a COMPLETED roll, reclaim the replaced versions'
        prefix entries from the fleet L2 (tfos.cachetier) — exact by
        key construction, never a flush: entries under other adapters/
        versions survive untouched. Runs only once every seat serves
        the target; mid-rollout the old version's keys are still live
        on unswapped seats. Best-effort: a down cache service just
        means the dead keys age out via LRU (they can never be looked
        up again — version is baked into every key)."""
        fleet = self._fleet
        if fleet is None:
            return
        old = {
            str(pr.version)
            for _, pr in swapped
            if pr is not None and str(pr.version) != str(update.version)
        }
        for ver in sorted(old):
            dropped = fleet.invalidate_prefix_version(ver)
            if dropped:
                logger.info(
                    "rollout of %r reclaimed %d prefix L2 entrie(s) "
                    "of prior version %r", update.version, dropped, ver,
                )

    def _sync_stragglers(self, update: WeightsUpdate) -> None:
        """Post-completion convergence pass: any READY seat still
        serving a different version (a respawn that rejoined before
        the target was set) is swapped in place. Failures are logged,
        never rolled back — the fleet-wide outcome already stands, and
        the gauge shows any seat left diverged."""
        for view in self._fleet.views():
            if view["state"] != READY:
                continue
            cur = self._handle_version(view["handle"])
            if cur is None or str(cur) == update.version:
                continue
            try:
                self._swap_seat(view["rid"], update)
            except _SeatFailure as f:
                logger.warning(
                    "straggler re-sync of replica %d to %r failed "
                    "(%s): %s — seat stays on %r",
                    f.rid, update.version, f.stage, f.cause, cur,
                )
                if f.held and not f.swapped:
                    try:
                        self._fleet.release_seat(f.rid)
                    except Exception:  # noqa: BLE001 - closed race
                        pass
                elif f.swapped:
                    # half-applied straggler: a respawn is the clean
                    # recovery (boot weights, then the hook re-applies
                    # the target)
                    try:
                        self._fleet.force_respawn(
                            f.rid, "straggler re-sync failed"
                        )
                    except Exception:  # noqa: BLE001 - closed race
                        logger.exception(
                            "straggler respawn of replica %d failed",
                            f.rid,
                        )

    @staticmethod
    def _seat_needs_path(handle) -> bool:
        """Subprocess-style seats can only consume PATH-published
        updates (the child loads the checkpoint in its own process;
        in-memory params never cross the boundary)."""
        return (
            getattr(handle, "engine", None) is None
            and not hasattr(handle, "swap_weights")
        )

    def _prior_of(self, view) -> WeightsUpdate | None:
        """The retained per-seat prior a rollback re-installs. For an
        in-process seat: a REFERENCE to the live tree (immutable jax
        arrays — retention is free). For a subprocess seat: the last
        path-published update this controller applied, or ``None``
        (rollback then respawns to the boot checkpoint, which IS the
        prior version)."""
        handle = view["handle"]
        eng = getattr(handle, "engine", None)
        if eng is not None and hasattr(eng, "current_weights"):
            ver, params = eng.current_weights()
            return WeightsUpdate(
                version=str(ver), kind="full", params=params
            )
        with self._lock:
            return self._applied.get(view["rid"])

    def _swap_seat(
        self, rid: int, update: WeightsUpdate
    ) -> WeightsUpdate | None:
        """Hold → drain → swap → verify → release ONE seat; returns the
        retained prior (captured under the hold). The hold comes FIRST
        and everything after it works on a FRESH view: a seat that
        drained and respawned between rollout placement and its turn
        would otherwise be swapped through its orphaned old handle —
        the held seat cannot change hands (the respawn supervisor only
        runs for seats that left READY through the probe/report paths,
        and ``hold_seat`` requires READY)."""
        fleet = self._fleet
        try:
            fleet.hold_seat(
                rid, reason=f"rollout to {update.version}"
            )
        except BaseException as e:  # noqa: BLE001 - seat flipped under us
            raise _SeatFailure(rid, "hold", e, False, False) from e
        try:
            view = next(
                v for v in fleet.views() if v["rid"] == rid
            )
            handle = view["handle"]
            prior = self._prior_of(view)
            self._await_quiescent(handle)
        except BaseException as e:  # noqa: BLE001 - drain timed out
            raise _SeatFailure(rid, "drain", e, True, False) from e
        t0 = time.monotonic()
        try:
            self._apply(handle, update)
        except _SeatFailure as f:
            f.prior = prior
            raise
        except BaseException as e:  # noqa: BLE001 - per-seat verdict
            # conservative `swapped` classification: subprocess reloads
            # may have installed before the child's warmup probe
            # failed, and an in-process swap_weights TIMEOUT means the
            # scheduler may still install the prepared tree after we
            # gave up — both need the restore path, not a bare release
            # (which would rejoin a possibly-new-version seat while the
            # rest of the fleet rolls back: the mixed wedge)
            swapped_flag = (
                getattr(handle, "engine", None) is None
                or isinstance(e, TimeoutError)
            )
            raise _SeatFailure(
                rid, "swap", e, True, swapped_flag, prior=prior
            ) from e
        try:
            failpoint("rollout.verify")
            self._verify(handle)
        except BaseException as e:  # noqa: BLE001 - health regression
            raise _SeatFailure(
                rid, "verify", e, True, True, prior=prior
            ) from e
        dur = time.monotonic() - t0
        self._h_swap.observe(dur)
        fleet.release_seat(rid)
        listener = fleet.listener
        if listener is not None:
            # the swapped engine's prefix cache was cleared; the
            # router's affinity entries describe the OLD weights
            listener.replica_reset(rid)
        self._record_applied(rid, update)
        flightrec.note(
            "replica_swap", replica=rid, version=update.version,
            swap_kind=update.kind, seconds=round(dur, 3),
            generation=view["generation"],
        )
        # every request in flight DURING the swap gets the rollout on
        # its own timeline — a trace spanning a version flip shows
        # exactly where it happened
        reqtrace.mark(
            "rollout.replica_swap", replica=rid,
            version=update.version,
        )
        logger.info(
            "replica %d -> %r in %.2fs", rid, update.version, dur
        )
        return prior

    def _restore_seat(
        self,
        rid: int,
        prior: WeightsUpdate | None,
        *,
        held: bool,
        swapped: bool,
    ) -> bool:
        """Bring one seat back to its retained prior after a failed
        rollout. Escalates to a full respawn (boot weights — the prior
        lineage) when the restore itself fails or no prior is
        retained."""
        fleet = self._fleet
        if not swapped:
            # weights never changed on this seat: just un-hold it
            if held:
                try:
                    fleet.release_seat(rid)
                except Exception:  # noqa: BLE001 - closed mid-rollback
                    logger.exception(
                        "rollback: releasing replica %d failed", rid
                    )
                    return False
            return True
        try:
            view = next(
                v for v in fleet.views() if v["rid"] == rid
            )
            if not held:
                if view["state"] != READY:
                    # the seat changed hands (probe drain/respawn) —
                    # its supervisor owns it now, and the respawn hook
                    # re-syncs it to the pre-roll target
                    return True
                fleet.hold_seat(rid, reason="rollout rollback")
                self._await_quiescent(view["handle"])
            if prior is None:
                raise RuntimeError(
                    "no retained prior for this seat (boot version "
                    "lives in the spawn argv) — respawning"
                )
            self._apply(view["handle"], prior)
            self._verify(view["handle"])
            fleet.release_seat(rid)
            listener = fleet.listener
            if listener is not None:
                listener.replica_reset(rid)
            self._record_applied(rid, prior)
            logger.info(
                "rollback: replica %d restored to %r", rid,
                prior.version,
            )
            return True
        except BaseException:  # noqa: BLE001 - escalate to respawn
            logger.exception(
                "rollback: restoring replica %d failed — respawning "
                "to boot weights", rid,
            )
            try:
                fleet.force_respawn(rid, "rollout rollback failed")
            except Exception:  # noqa: BLE001 - teardown race
                logger.exception(
                    "rollback: respawn of replica %d failed", rid
                )
            return False

    # -- seat plumbing -------------------------------------------------

    def _await_quiescent(self, handle) -> None:
        deadline = time.monotonic() + self._drain_timeout
        while handle.unresolved() > 0:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"seat did not quiesce within "
                    f"{self._drain_timeout}s (unresolved="
                    f"{handle.unresolved()})"
                )
            time.sleep(0.05)

    def _resolve_params(self, update: WeightsUpdate):
        if update.params is not None:
            return update.params
        if self._loader is None:
            raise RuntimeError(
                "path-published update needs loader= for in-process "
                "replicas (see rollout.checkpoint_loader)"
            )
        return self._loader(update)

    def _apply(self, handle, update: WeightsUpdate) -> None:
        """Install ``update`` on one replica handle (or bare engine),
        including the re-warm probe. Raises :class:`_SeatFailure` with
        ``swapped`` set precisely for in-process seats (an install that
        never happened must not trigger a restore)."""
        eng = getattr(handle, "engine", None)
        if eng is None and hasattr(handle, "swap_weights"):
            eng = handle  # bare engine target
        if eng is not None:
            params = self._resolve_params(update)  # not yet swapped
            eng.swap_weights(
                params, version=update.version, kind=update.kind,
                timeout=self._swap_timeout,
            )
            if self._warmup_probe:
                try:
                    # the re-warm: one throwaway decode proves the new
                    # tree actually runs (compiles are shape-cached, so
                    # this is one block of real compute, not a rebuild).
                    # BOUNDED like every other stage: a decode that
                    # hangs under the new weights must become a
                    # rollback, not a forever-held seat + a wedged
                    # _roll_lock no future version can ever take
                    eng.submit(
                        [0], 2, eos_id=-1,
                        deadline_s=self._verify_timeout,
                    )
                except BaseException as e:
                    raise _SeatFailure(
                        getattr(handle, "rid", 0), "warmup", e, True,
                        True,
                    ) from e
            return
        if update.path is None:
            raise WeightsIncompatible(
                "subprocess replicas need a path-published update "
                "(use publish_params/publish_checkpoint so the child "
                "can load it)"
            )
        handle.reload(
            version=update.version, kind=update.kind, path=update.path,
            step=update.step, timeout=self._swap_timeout,
        )

    def _verify(self, handle) -> None:
        """The rejoin gate: the replica's OWN readiness (its
        ``/readyz`` equivalent), bounded. A replica that cannot verify
        does not rejoin — it rolls back."""
        deadline = time.monotonic() + self._verify_timeout
        while True:
            h = handle.health()
            if h.get("ready"):
                return
            if not h.get("live", True):
                raise RuntimeError(
                    "replica died during post-swap verification"
                )
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica not ready within {self._verify_timeout}s "
                    "after swap"
                )
            time.sleep(0.05)

    # -- single-engine target ------------------------------------------

    def _roll_single(self, update: WeightsUpdate) -> str:
        eng = self._engine
        ver, prior_params = eng.current_weights()
        prior = WeightsUpdate(
            version=str(ver), kind="full", params=prior_params
        )
        t0 = time.monotonic()
        swapped = False
        try:
            failpoint("rollout.swap")
            self._apply(eng, update)
            swapped = True
            failpoint("rollout.verify")
            self._verify(eng)
        except BaseException as e:  # noqa: BLE001 - roll back in place
            if isinstance(e, _SeatFailure):
                cause = e.cause
                # _apply's warmup probe fails AFTER the install
                swapped = swapped or e.swapped
            else:
                cause = e
            logger.error(
                "single-engine rollout of %r failed: %r — rolling "
                "back to %r", update.version, cause, prior.version,
            )
            flightrec.note(
                "rollout_rollback", version=update.version,
                failed_replica=0, stage="swap", error=repr(cause),
                swapped=[0] if swapped else [],
            )
            if swapped:
                try:
                    eng.swap_weights(
                        prior.params, version=prior.version,
                        kind="full", timeout=self._swap_timeout,
                    )
                except Exception:  # noqa: BLE001 - keep the engine's word
                    logger.exception(
                        "single-engine rollback failed; engine may be "
                        "serving a partially verified version"
                    )
            # not swapped: the engine was never touched (load failure /
            # WeightsIncompatible) — re-installing the prior would only
            # drain the pipeline window and flush the warm prefix cache
            self._note_error(cause, "swap")
            self._count_outcome("rolled_back")
            flightrec.dump_now(f"rollout_rollback:{update.version}")
            return "rolled_back"
        self._h_swap.observe(time.monotonic() - t0)
        self._record_applied(0, update)
        with self._lock:
            self._target_update = update
        if str(prior.version) != str(update.version):
            # same reclamation contract as the fleet path — the
            # engine's own L2 facade, when one is attached
            l2 = getattr(eng, "_prefix_l2", None)
            if l2 is not None:
                l2.invalidate_version(prior.version)
        self._note_error(None, "")
        self._count_outcome("completed")
        flightrec.note(
            "replica_swap", replica=0, version=update.version,
            swap_kind=update.kind,
        )
        reqtrace.mark(
            "rollout.replica_swap", replica=0, version=update.version
        )
        flightrec.note("rollout_complete", version=update.version)
        return "completed"

    # -- respawn re-sync (ServingFleet.rollout_hook) -------------------

    def _on_respawn(self, rid: int, handle) -> None:
        """A seat respawned (SIGKILL chaos, watchdog wedge) while this
        controller owns the fleet's target version: bring the fresh
        replica — booted on the original checkpoint — to the current
        target BEFORE it becomes routable. Runs on the fleet's respawn
        thread; failures are logged by the fleet and the seat rejoins
        on its boot weights (the gauge shows the divergence)."""
        with self._lock:
            target = self._target_update
        if target is None:
            return
        cur = self._handle_version(handle)
        if cur is not None and str(cur) == target.version:
            return
        self._apply(handle, target)
        self._verify(handle)
        self._record_applied(rid, target)
        logger.info(
            "respawned replica %d re-synced to %r", rid,
            target.version,
        )
