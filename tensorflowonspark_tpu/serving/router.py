"""Health-routed request router over a :class:`ServingFleet`.

The client-facing half of the fleet plane: one object with the
engine's surface (``submit`` / ``submit_many`` / ``stream`` /
``stats`` / ``metrics`` / ``close``) that owns N replicas behind it —
``serve_model --gen-replicas N`` drops it in where the single engine
sat. Three policies live here:

- **Placement** is prefix-aware, then load-balanced: the router keeps
  an adapter-bucketed prefix index (:class:`_AffinityIndex`, the
  ``_PrefixStore`` lookup technique applied to routing) mapping every
  dispatched prompt to its replica, probed longest-prefix-first — a
  request extending a prompt some replica already served goes back to
  the replica whose ``_PrefixStore`` is warm. Ties (and misses) break
  on the per-replica load signal — queue depth + busy slots from the
  fleet's probe stats plus the router's own outstanding-dispatch
  count, the MetricsAggregator-style merged view — then
  deterministically on replica id.

- **Admission / shedding** makes the per-request ``deadline_s`` a
  policy, not just a timeout: from queue-depth estimates and an EWMA
  of observed request durations the router rejects ON ARRIVAL
  (:class:`FleetOverloaded` → HTTP 429 + Retry-After) any request no
  replica can finish inside its deadline — p99 of ADMITTED requests
  stays bounded under overload instead of the whole queue collapsing.
  During a full-fleet drain every request sheds with
  :class:`FleetUnavailable` (→ HTTP 503).

- **Failover** retries an IDEMPOTENT request exactly once on a
  different healthy replica. Idempotent means no sampling side-effect
  has been consumed yet: a blocking ``submit``/``submit_many`` whose
  reply never arrived, or a stream that has not yielded its first
  token. A mid-stream failure is never silently retried (the consumer
  already observed tokens) and never hangs: it delivers exactly one
  terminal error. Every failover also reports the replica to the
  fleet, which drains and respawns it.

Failpoint ``fleet.dispatch`` sits on the dispatch edge; its ``drop``
action simulates a dispatch lost in flight, which MUST surface as a
loud terminal/failover — the router treats it as :class:`ReplicaGone`.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict

from tensorflowonspark_tpu.obs import flightrec, reqtrace

from tensorflowonspark_tpu.serving.engine import (
    EngineOverloaded,
    EngineWedged,
)
from tensorflowonspark_tpu.serving.fleet import (
    READY,
    FleetOverloaded,
    FleetUnavailable,
    ReplicaGone,
    ServingFleet,
)
from tensorflowonspark_tpu.utils.failpoints import (
    FailpointError,
    failpoint,
)

logger = logging.getLogger(__name__)

__all__ = ["FleetRouter"]

# Failures that mean "this replica, not this request": eligible for
# one transparent failover while the request is still idempotent.
# FailpointError/ConnectionError cover armed chaos and severed
# transports the handle layer didn't already wrap.
_FAILOVER_ERRORS = (
    EngineWedged,
    ReplicaGone,
    FailpointError,
    ConnectionError,
)


class _AffinityIndex:
    """Prompt-prefix → replica map, adapter-bucketed with per-length
    hash probes (the ``_PrefixStore`` index structure, reused for
    routing): ``lookup`` probes the prompt's prefixes longest-first,
    one tuple hash per distinct stored length, so a warm index costs
    O(distinct lengths) per placement, not O(entries). LRU-capped;
    entries for a respawned (cold) replica are dropped wholesale.
    Callers hold the router lock."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, int(capacity))
        self._d: "OrderedDict[tuple, int]" = OrderedDict()
        # adapter -> {prefix_length -> set of stored key tuples}
        self._by_adapter: dict[int, dict[int, set]] = {}

    def lookup(self, tokens, adapter: int) -> int | None:
        n = len(tokens)
        by_len = self._by_adapter.get(adapter)
        if not by_len:
            return None
        for lk in sorted(by_len, reverse=True):
            if lk > n:
                continue
            cand = tuple(tokens[:lk])
            if cand in by_len[lk]:
                k = (adapter, cand)
                self._d.move_to_end(k)
                return self._d[k]
        return None

    def record(self, tokens, adapter: int, rid: int) -> None:
        key = tuple(tokens)
        k = (adapter, key)
        if k not in self._d:
            self._by_adapter.setdefault(adapter, {}).setdefault(
                len(key), set()
            ).add(key)
        self._d[k] = rid
        self._d.move_to_end(k)
        while len(self._d) > self.capacity:
            (ad, old), _ = self._d.popitem(last=False)
            self._unindex(ad, old)

    def _unindex(self, adapter: int, key: tuple) -> None:
        by_len = self._by_adapter[adapter]
        bucket = by_len[len(key)]
        bucket.discard(key)
        if not bucket:
            del by_len[len(key)]
            if not by_len:
                del self._by_adapter[adapter]

    def drop_replica(self, rid: int) -> None:
        stale = [k for k, v in self._d.items() if v == rid]
        for k in stale:
            del self._d[k]
            self._unindex(*k)

    def __len__(self) -> int:
        return len(self._d)


class _MetricsView:
    """Duck-typed stand-in for an engine's ``.metrics`` registry:
    ``render()`` returns the MERGED exposition (fleet/router series +
    every replica's engine series re-labelled ``replica="<rid>"``) so
    ``serve_model``'s ``/metrics`` handler works unchanged."""

    def __init__(self, router: "FleetRouter"):
        self._router = router

    def render(self) -> str:
        return self._router.metrics_text()


class FleetRouter:
    """See the module docstring. Shared state (`_outstanding`,
    `_est_req_s`, the affinity index, shed/failover tallies) is
    guarded by ``self._lock``; nothing blocking runs under it."""

    #: serve_model switches its /stats mode label on this
    IS_FLEET = True

    def __init__(
        self,
        fleet: ServingFleet,
        *,
        default_temperature: float = 0.0,
        affinity_capacity: int = 512,
        affinity_load_slack: float = 1.0,
        service_time_hint_s: float | None = None,
        ewma_alpha: float = 0.3,
    ):
        self._fleet = fleet
        # serve_model's n>1 greedy check reads the configured default
        # temperature off the engine object it fronts; mirror it
        self._temperature = float(default_temperature)
        self._service_time_hint = (
            None
            if service_time_hint_s is None
            else float(service_time_hint_s)
        )
        self._ewma_alpha = float(ewma_alpha)
        self._lock = threading.Lock()
        self._affinity = _AffinityIndex(affinity_capacity)  # guarded-by: self._lock
        self._outstanding: dict[int, int] = {}  # guarded-by: self._lock
        self._est_req_s: dict[int, float] = {}  # guarded-by: self._lock
        # Measured fleet-wide service-time seed (history percentile or
        # an autotune install); beats the ctor's hardcoded hint in the
        # cold-start estimate chain — see _wait_estimate.
        self._seed_est_s: float | None = None  # guarded-by: self._lock
        self._shed_counts: dict[str, int] = {}  # guarded-by: self._lock
        self._failovers = 0  # guarded-by: self._lock
        self._affinity_hits = 0  # guarded-by: self._lock
        self._affinity_misses = 0  # guarded-by: self._lock
        self._affinity_bypasses = 0  # guarded-by: self._lock
        # With a fleet-global prefix L2 behind every replica
        # (tfos.cachetier), a "cold" replica recovers a warm prefix
        # from L2 instead of re-prefilling — so prefix affinity demotes
        # from placement-correctness to a cache-LOCALITY hint, and the
        # warm pick yields to the least-loaded replica whenever the
        # normalized load skew exceeds this slack.
        self._affinity_is_hint = (
            getattr(fleet, "_l2_spec", None) is not None
        )
        self._affinity_load_slack = float(affinity_load_slack)

        reg = fleet.metrics
        self._m_requests = reg.counter(
            "router_requests_total",
            "routed requests by replica and outcome",
        )
        self._m_shed = reg.counter(
            "router_shed_total",
            "requests rejected at admission, by reason",
        )
        self._m_failover = reg.counter(
            "router_failover_total",
            "idempotent requests transparently retried on another "
            "replica",
        )
        self._m_affinity = reg.counter(
            "router_affinity_total",
            "prefix-affinity placements by outcome (hit/miss/bypass — "
            "bypass = warm replica yielded to least-loaded because a "
            "prefix L2 makes the miss recoverable)",
        )
        self._g_depth = reg.gauge(
            "router_queue_depth",
            "requests dispatched by the router and not yet resolved",
        )
        self._h_latency = reg.histogram(
            "router_request_seconds",
            "end-to-end latency of successfully routed requests "
            "(placement through reply) — the fleet_latency SLO "
            "substrate",
        )

        def _collect(depth=self._g_depth):
            with self._lock:
                depth.set(sum(self._outstanding.values()))

        reg.add_collector(_collect)
        self._collector = _collect
        fleet.listener = self

    # -- fleet callbacks ----------------------------------------------

    def replica_reset(self, rid: int) -> None:
        """A seat's engine was replaced (respawn): everything the
        router learned about the OLD engine — prefix warmth, service
        rate — is stale."""
        with self._lock:
            self._affinity.drop_replica(rid)
            self._est_req_s.pop(rid, None)

    # -- service estimate (autotune actuation / cold-start seed) ------

    def set_service_estimate(self, seconds: float) -> float:
        """Install a measured fleet-wide service-time seed — the
        autotune actuation path for the ``router.service_estimate_s``
        knob. It replaces the ctor's ``service_time_hint_s`` guess in
        the admission estimate chain for replicas with no per-replica
        EWMA yet; replicas with observed completions keep their own
        EWMAs (this is a cold-start floor, not an override)."""
        v = float(seconds)
        if v <= 0:
            raise ValueError(
                f"service estimate must be > 0 seconds, got {seconds}"
            )
        with self._lock:
            self._seed_est_s = v
        return v

    def service_estimate(self) -> float:
        """The cold-start service estimate currently in effect (the
        knob readback): the measured seed when installed, else the
        ctor hint, else 0.0 (no estimate — admission can't judge)."""
        with self._lock:
            return self._seed_est_s or self._service_time_hint or 0.0

    def seed_from_history(
        self,
        history,
        *,
        metric: str = "router_request_seconds",
        q: float = 0.9,
        window_s: float = 60.0,
        now: float | None = None,
    ) -> float | None:
        """Seed the admission estimate from the measured duration
        distribution: the ``q``-quantile of the request-latency
        histogram over the trailing window, when one exists. Returns
        the installed seed, or None (no in-window signal — the chain
        keeps its current fallbacks). The percentile scan runs OUTSIDE
        ``self._lock`` (History takes its own lock; nothing blocking
        runs under ours)."""
        est = history.percentile(metric, q, window_s=window_s, now=now)
        if est is None or est <= 0.0:
            return None
        with self._lock:
            self._seed_est_s = float(est)
        return float(est)

    # -- placement / admission ----------------------------------------

    @staticmethod
    def _load(view: dict, outstanding: int) -> float:
        st = view["stats"] or {}
        slots = max(1, int(st.get("slots") or 1))
        return (
            int(st.get("queue_depth") or 0)
            + int(st.get("slots_busy") or 0)
            + outstanding
        ) / slots

    def _wait_estimate(self, view: dict, outstanding: int) -> float:  # lint: holds-lock
        """Expected completion latency of a NEW request on this
        replica, from queue-depth + an EWMA of observed request
        durations. Before any completion lands on a replica the chain
        falls back to the MEASURED fleet-wide seed (history percentile
        via ``seed_from_history`` / ``set_service_estimate``) and only
        then to the ctor's hardcoded ``service_time_hint_s`` guess —
        a stale pessimistic hint otherwise sheds feasible requests on
        every cold start (fresh replica, respawn, or router restart).
        0.0 = no estimate yet — admit (can't judge). Callers hold
        ``self._lock``."""
        rate = (
            self._est_req_s.get(view["rid"])
            or self._seed_est_s
            or self._service_time_hint
        )
        if not rate:
            return 0.0
        st = view["stats"] or {}
        slots = max(1, int(st.get("slots") or 1))
        depth = int(st.get("queue_depth") or 0) + int(
            st.get("slots_busy") or 0
        )
        depth = max(depth, outstanding)
        return rate * (depth / slots + 1.0)

    def _shed(self, reason: str, trace: str | None = None) -> None:  # lint: holds-lock
        # callers hold self._lock (counter inc nests the metric's own
        # lock under ours; nothing ever nests the other way)
        self._m_shed.inc(reason=reason)
        first = reason not in self._shed_counts
        self._shed_counts[reason] = self._shed_counts.get(reason, 0) + 1
        # the shed decision is attributed on the victim's trace (and
        # the trace id rides the flight-recorder event, so a
        # postmortem joins the two planes by id)
        flightrec.note("fleet_shed", reason=reason, trace=trace)
        reqtrace.event(trace, "router.shed", reason=reason)
        if first:
            # shedding beginning (per reason) is an incident: persist
            # the record — on a daemon thread, the dump's IO must not
            # sit on the request path (or under self._lock)
            threading.Thread(
                target=flightrec.dump_now,
                args=(f"fleet_shed:{reason}",),
                daemon=True,
            ).start()

    def _place(self, tokens, adapter: int, deadline_s, exclude, trace=None):
        """Pick the replica for one request: affinity first, then
        least-loaded; deadline admission on the pick (affinity yields
        to feasibility). Bumps the pick's outstanding count and
        records the prompt in the affinity index before returning."""
        if self._fleet.draining or self._fleet.closed:
            with self._lock:
                self._shed("drain", trace=trace)
            raise FleetUnavailable(
                "fleet is draining; no new requests are admitted"
            )
        ready = [
            v
            for v in self._fleet.ready_views()
            if v["rid"] not in exclude
        ]
        if not ready:
            with self._lock:
                self._shed("no_ready", trace=trace)
            raise FleetUnavailable("no ready replica")
        with self._lock:
            outstanding = {
                v["rid"]: self._outstanding.get(v["rid"], 0)
                for v in ready
            }
            hit_rid = self._affinity.lookup(tokens, adapter)
            pick = None
            if hit_rid is not None:
                for v in ready:
                    if v["rid"] == hit_rid:
                        pick = v
                        break
            bypassed = False
            if pick is not None and self._affinity_is_hint:
                # L2 configured: a miss here is recoverable, so the
                # warm replica only wins while roughly as idle as the
                # least-loaded one (see the ctor comment).
                least = min(
                    ready,
                    key=lambda v: (
                        self._load(v, outstanding[v["rid"]]),
                        v["rid"],
                    ),
                )
                skew = self._load(
                    pick, outstanding[pick["rid"]]
                ) - self._load(least, outstanding[least["rid"]])
                if (
                    least["rid"] != pick["rid"]
                    and skew > self._affinity_load_slack
                ):
                    bypassed = True
                    pick = least
            if bypassed:
                self._affinity_bypasses += 1
                self._m_affinity.inc(outcome="bypass")
            elif pick is not None:
                self._affinity_hits += 1
                self._m_affinity.inc(outcome="hit")
            else:
                self._affinity_misses += 1
                self._m_affinity.inc(outcome="miss")
                pick = min(
                    ready,
                    key=lambda v: (
                        self._load(v, outstanding[v["rid"]]),
                        v["rid"],
                    ),
                )
            if deadline_s is not None:
                est = self._wait_estimate(
                    pick, outstanding[pick["rid"]]
                )
                if est > float(deadline_s):
                    # the warm replica can't make it — feasibility
                    # beats affinity
                    waits = {
                        v["rid"]: self._wait_estimate(
                            v, outstanding[v["rid"]]
                        )
                        for v in ready
                    }
                    alt = min(
                        ready,
                        key=lambda v: (waits[v["rid"]], v["rid"]),
                    )
                    est_alt = waits[alt["rid"]]
                    if est_alt > float(deadline_s):
                        self._shed("deadline", trace=trace)
                        raise FleetOverloaded(
                            f"deadline_s={deadline_s} cannot be met: "
                            f"best replica's estimated completion is "
                            f"{est_alt:.2f}s",
                            retry_after=est_alt - float(deadline_s),
                        )
                    pick = alt
            rid = pick["rid"]
            self._outstanding[rid] = self._outstanding.get(rid, 0) + 1
            self._affinity.record(tokens, adapter, rid)
        return pick

    def _resolve(self, rid: int, outcome: str, t0=None) -> None:
        self._m_requests.inc(replica=str(rid), outcome=outcome)
        dur = None
        if outcome == "ok" and t0 is not None:
            dur = time.monotonic() - t0
            self._h_latency.observe(dur)
        with self._lock:
            n = self._outstanding.get(rid, 0)
            if n > 0:
                self._outstanding[rid] = n - 1
            if dur is not None:
                prev = self._est_req_s.get(rid)
                self._est_req_s[rid] = (
                    dur
                    if prev is None
                    else (1 - self._ewma_alpha) * prev
                    + self._ewma_alpha * dur
                )

    def _note_failover(self) -> None:
        self._m_failover.inc()
        with self._lock:
            self._failovers += 1

    # -- request surface ----------------------------------------------

    def submit(self, tokens, max_new_tokens, **kw):
        want_lp = bool(kw.pop("return_logprobs", False))
        out = self.submit_many(
            [tokens], max_new_tokens, return_logprobs=want_lp, **kw
        )
        if want_lp:
            comps, lps = out
            return comps[0], lps[0]
        return out[0]

    def submit_many(self, prompts, max_new_tokens, **kw):
        """Blocking decode of a request's rows on ONE replica (the
        engine's atomic-admission contract is per replica). Failures
        before the reply (wedge, severed replica, armed dispatch
        failpoint) fail over exactly once — no token ever reached the
        caller, so the retry is invisible; the failing replica drains
        and respawns.

        A ``trace=`` kwarg (or a fresh mint when tracing is on) rides
        the whole routed lifetime: placement, each failover hop, and
        the dispatch to the replica happen on the SAME trace — the
        replica handle forwards the id to the engine (in-process) or
        across the wire as ``X-TFOS-Trace`` (subprocess)."""
        if not prompts:
            raise ValueError("prompts must be a non-empty list")
        tid, owned = reqtrace.ensure(kw.pop("trace", None), route="submit")
        if tid is not None:
            kw["trace"] = tid
        t_req = time.monotonic()
        try:
            out = self._submit_many_routed(prompts, max_new_tokens, tid, kw)
        except BaseException as e:
            reqtrace.flag(tid, error=type(e).__name__)
            if owned:
                reqtrace.finish(
                    tid, outcome="error", error=type(e).__name__
                )
            raise
        reqtrace.segment(tid, "router.submit", time.monotonic() - t_req)
        if owned:
            reqtrace.finish(tid, outcome="ok")
        return out

    def _submit_many_routed(self, prompts, max_new_tokens, tid, kw):
        adapter = int(kw.get("adapter") or 0)
        deadline_s = kw.get("deadline_s")
        tried: set[int] = set()
        last_err: BaseException | None = None
        for attempt in (0, 1):
            try:
                pick = self._place(
                    prompts[0], adapter, deadline_s, tried, trace=tid
                )
            except FleetUnavailable:
                if isinstance(last_err, EngineOverloaded):
                    with self._lock:
                        self._shed("queue_full", trace=tid)
                    raise FleetOverloaded(
                        "every routable replica's queue is full"
                    ) from last_err
                if last_err is not None:
                    raise last_err from None
                raise
            reqtrace.event(
                tid, "router.place",
                replica=pick["rid"], attempt=attempt,
            )
            t0 = time.monotonic()
            try:
                if failpoint("fleet.dispatch") == "drop":
                    # a dropped dispatch must be a LOUD terminal (or a
                    # transparent failover), never a hang
                    raise ReplicaGone(
                        f'dispatch to replica {pick["rid"]} dropped '
                        "(failpoint fleet.dispatch)"
                    )
                out = pick["handle"].submit_many(
                    prompts, max_new_tokens, **kw
                )
            except _FAILOVER_ERRORS as e:
                self._resolve(
                    pick["rid"], "failover" if attempt == 0 else "error"
                )
                self._fleet.report_failure(
                    pick["rid"], repr(e),
                    generation=pick["generation"],
                )
                tried.add(pick["rid"])
                last_err = e
                if attempt == 0:
                    self._note_failover()
                    reqtrace.event(
                        tid, "router.failover",
                        replica=pick["rid"], error=type(e).__name__,
                    )
                    reqtrace.flag(tid, failover=True)
                    continue
                raise
            except EngineOverloaded as e:
                self._resolve(pick["rid"], "overloaded")
                tried.add(pick["rid"])
                last_err = e
                if attempt == 0:
                    continue
                with self._lock:
                    self._shed("queue_full", trace=tid)
                raise FleetOverloaded(
                    f"every routable replica's queue is full: {e}"
                ) from e
            except BaseException:
                self._resolve(pick["rid"], "error")
                raise
            else:
                self._resolve(pick["rid"], "ok", t0)
                return out
        raise last_err  # pragma: no cover - loop always returns/raises

    def stream(self, tokens, max_new_tokens, **kw):
        """Streaming decode with pre-first-token failover: connect (and
        anything before the first yielded token) may transparently
        retry ONCE on another replica; once a token has been consumed
        the request is no longer idempotent and any failure delivers
        exactly one terminal error."""
        return _RoutedStream(self, tokens, max_new_tokens, kw)

    # -- observability -------------------------------------------------

    @property
    def fleet(self) -> ServingFleet:
        """The fleet behind this router (rollout controllers target
        it; the router stays the request-path surface)."""
        return self._fleet

    def health(self) -> dict:
        return self._fleet.health()

    def stats(self) -> dict:
        with self._lock:
            router = {
                "outstanding": {
                    str(k): v
                    for k, v in sorted(self._outstanding.items())
                    if v
                },
                "est_request_s": {
                    str(k): round(v, 4)
                    for k, v in sorted(self._est_req_s.items())
                },
                "failovers": self._failovers,
                "shed": dict(self._shed_counts),
                "affinity_hits": self._affinity_hits,
                "affinity_misses": self._affinity_misses,
                "affinity_bypasses": self._affinity_bypasses,
                "affinity_entries": len(self._affinity),
            }
        return {"fleet": self._fleet.stats(), "router": router}

    @property
    def metrics(self) -> _MetricsView:
        return _MetricsView(self)

    def metrics_text(self) -> str:
        """Fleet/router series + every replica's engine series merged
        into ONE exposition, each sample re-labelled
        ``replica="<rid>"`` — the MetricsAggregator merge discipline
        applied to replicas instead of cluster nodes."""
        from tensorflowonspark_tpu.obs.cluster import (
            merge_families,
            parse_prometheus_text,
        )

        per: dict[str, dict] = {}
        for v in self._fleet.views():
            try:
                per[str(v["rid"])] = parse_prometheus_text(
                    v["handle"].metrics_text()
                )
            except Exception as e:  # noqa: BLE001 - a dead replica's
                # series are simply absent this round
                logger.debug(
                    "replica %s metrics unavailable: %s", v["rid"], e
                )
        return self._fleet.metrics.render() + merge_families(
            per, label="replica"
        )

    # -- lifecycle -----------------------------------------------------

    def begin_drain(self) -> None:
        self._fleet.begin_drain()

    def close(self, drain: bool = False, drain_timeout: float = 300.0):
        self._fleet.metrics.remove_collector(self._collector)
        self._fleet.close(drain=drain, timeout=drain_timeout)


class _RoutedStream:
    """Router-side stream handle mirroring the engine ``_Stream``
    surface (``close`` / ``result`` / ``logprobs``)."""

    def __init__(self, router: FleetRouter, tokens, max_new, kw):
        self._router = router
        self._tokens = list(tokens)
        self._max_new = max_new
        # adopt (or mint) the request trace before kw is forwarded —
        # the id rides kw into the replica handle so connect retries
        # and the eventual engine segments land on the SAME trace
        self._trace, self._trace_owned = reqtrace.ensure(
            kw.pop("trace", None), route="stream"
        )
        self._trace_done = False
        if self._trace is not None:
            kw["trace"] = self._trace
        self._kw = kw
        self._adapter = int(kw.get("adapter") or 0)
        self._deadline = kw.get("deadline_s")
        self._tried: set[int] = set()
        self._failed_over = False
        self._overload_err: EngineOverloaded | None = None
        self._yielded = 0
        # _resolved means: the outstanding count _place bumped for the
        # CURRENT _rid has been released (exactly-once accounting).
        # True while no dispatch is held — _open flips it False after
        # each successful placement.
        self._resolved = True
        self._inner = None
        self._rid: int | None = None
        self._gen: int | None = None
        self._t0: float | None = None
        self._t_req = time.monotonic()
        try:
            self._open()
        except BaseException as e:
            self._trace_finish("error", error=type(e).__name__)
            raise

    def _open(self) -> None:
        """Place + connect. Failover-eligible connect failures consume
        the single failover budget; an overloaded replica is retried
        once on another (stream/submit parity — nothing has been sent
        to the client yet at open time); anything else propagates
        eagerly (the HTTP caller needs its 400/429/503 before
        committing a 200)."""
        while True:
            try:
                pick = self._router._place(
                    self._tokens, self._adapter, self._deadline,
                    self._tried, trace=self._trace,
                )
            except FleetUnavailable:
                if isinstance(self._overload_err, EngineOverloaded):
                    with self._router._lock:
                        self._router._shed(
                            "queue_full", trace=self._trace
                        )
                    raise FleetOverloaded(
                        "every routable replica's queue is full"
                    ) from self._overload_err
                if self._failed_over:
                    # the failover target pool ran dry: terminal
                    raise ReplicaGone(
                        "no replica left to fail over to"
                    ) from None
                raise
            reqtrace.event(
                self._trace, "router.place", replica=pick["rid"]
            )
            self._rid = pick["rid"]
            self._gen = pick["generation"]
            self._t0 = time.monotonic()
            self._resolved = False  # one outstanding held for _rid
            try:
                if failpoint("fleet.dispatch") == "drop":
                    raise ReplicaGone(
                        f'dispatch to replica {pick["rid"]} dropped '
                        "(failpoint fleet.dispatch)"
                    )
                self._inner = pick["handle"].stream(
                    self._tokens, self._max_new, **self._kw
                )
            except _FAILOVER_ERRORS as e:
                self._router._fleet.report_failure(
                    pick["rid"], repr(e),
                    generation=pick["generation"],
                )
                self._tried.add(pick["rid"])
                if not self._failed_over:
                    self._router._resolve(pick["rid"], "failover")
                    self._resolved = True
                    self._failed_over = True
                    self._router._note_failover()
                    reqtrace.event(
                        self._trace, "router.failover",
                        replica=pick["rid"],
                        error=type(e).__name__,
                    )
                    reqtrace.flag(self._trace, failover=True)
                    continue
                self._router._resolve(pick["rid"], "error")
                self._resolved = True
                raise
            except EngineOverloaded as e:
                # submit_many parity: one retry on another replica,
                # then a 429-class FleetOverloaded (not a bare 503)
                self._router._resolve(pick["rid"], "overloaded")
                self._resolved = True
                self._tried.add(pick["rid"])
                if self._overload_err is None:
                    self._overload_err = e
                    continue
                with self._router._lock:
                    self._router._shed(
                        "queue_full", trace=self._trace
                    )
                raise FleetOverloaded(
                    f"every routable replica's queue is full: {e}"
                ) from e
            except BaseException:
                self._router._resolve(pick["rid"], "error")
                self._resolved = True
                raise
            return

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            try:
                item = next(self._inner)
            except StopIteration:
                self._finish("ok")
                raise
            except _FAILOVER_ERRORS as e:
                self._router._fleet.report_failure(
                    self._rid, repr(e), generation=self._gen
                )
                if self._yielded == 0 and not self._failed_over:
                    # still idempotent: no token reached the consumer.
                    # The failed dispatch's outstanding is released
                    # HERE; _open re-arms _resolved only when it holds
                    # a new one — a terminal raise out of _open (e.g.
                    # no replica left) must not let close() release
                    # this rid a second time.
                    self._router._resolve(self._rid, "failover")
                    self._resolved = True
                    self._failed_over = True
                    self._router._note_failover()
                    reqtrace.event(
                        self._trace, "router.failover",
                        replica=self._rid,
                        error=type(e).__name__,
                    )
                    reqtrace.flag(self._trace, failover=True)
                    self._tried.add(self._rid)
                    try:
                        self._open()  # raises terminally if it can't
                    except BaseException as te:
                        self._trace_finish(
                            "error", error=type(te).__name__
                        )
                        raise
                    continue
                # mid-stream (or budget spent): exactly ONE terminal
                self._finish("error")
                raise
            except BaseException:
                self._finish("error")
                raise
            else:
                self._yielded += 1
                return item

    def _finish(self, outcome: str) -> None:
        if not self._resolved:
            self._resolved = True
            self._router._resolve(
                self._rid, outcome,
                self._t0 if outcome == "ok" else None,
            )
        self._trace_finish(outcome)

    def _trace_finish(self, outcome: str, **detail) -> None:
        """Terminal trace stamp — idempotent, because the accounting
        terminal (:meth:`_finish`) and the exception terminals (a
        raise out of ``_open``) can both fire for one stream."""
        if self._trace is None or self._trace_done:
            return
        self._trace_done = True
        reqtrace.segment(
            self._trace, "router.stream",
            time.monotonic() - self._t_req,
        )
        if outcome == "error":
            reqtrace.flag(
                self._trace, error=detail.get("error", True)
            )
        if self._trace_owned:
            reqtrace.finish(
                self._trace, outcome=outcome,
                tokens=self._yielded, **detail,
            )

    @property
    def result(self):
        return None if self._inner is None else self._inner.result

    @property
    def logprobs(self):
        return None if self._inner is None else self._inner.logprobs

    @property
    def weights_version(self):
        """The serving replica's per-request weights stamp (rollout
        coherence surface) — None until the stream resolves."""
        return (
            None
            if self._inner is None
            else getattr(self._inner, "weights_version", None)
        )

    def close(self) -> None:
        if self._inner is not None:
            self._inner.close()
        if not self._resolved and self._rid is not None:
            self._finish("cancelled")

    __del__ = close
